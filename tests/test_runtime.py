"""Step-pipelining runtime (apex_tpu.runtime) — the CPU-backend tier-1
matrix the ISSUE-2 acceptance names: K in {1, 4}, ragged epoch tails,
and a dynamic-loss-scale overflow skip mid-window, each pinned to ONE
compile per (K, shape) with ``prof.assert_trace_count`` and checked
bit-for-bit against the jitted-per-step reference trajectory.

Also the donation contract: ``chain_steps`` under
``donate_argnums=(0, 1)`` must actually release the stacked window
buffer (the [K, ...] stack is K full batches of HBM — the whole point
of donating it), and ``StepPipeline(donate_window=False)`` must leave a
reused pool window alive.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import runtime, training
from apex_tpu.prof import assert_trace_count
from apex_tpu.training import chain_steps, make_train_step


def _loss_fn(p, batch):
    x, y = batch
    return jnp.mean((x @ p["w"] - y) ** 2)


def _params():
    return {"w": jnp.ones((4, 2), jnp.float32)}


def _batches(n, seed=0, bad_step=None):
    """n per-step batches; ``bad_step`` gets an inf target so the
    dynamic scaler overflows exactly there."""
    rng = np.random.RandomState(seed)
    out = [(rng.randn(8, 4).astype(np.float32),
            rng.randn(8, 2).astype(np.float32)) for _ in range(n)]
    if bad_step is not None:
        x, y = out[bad_step]
        out[bad_step] = (x, np.full_like(y, np.inf))
    return out


def _reference(step_fn, state, batches):
    """The jitted-per-step trajectory the pipeline must reproduce."""
    step = jax.jit(step_fn)
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(m["loss"])
    return state, np.asarray(jax.device_get(losses))


def _assert_states_match(got, want):
    for g, w in zip(jax.tree_util.tree_leaves(got.params),
                    jax.tree_util.tree_leaves(want.params)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-5)


@pytest.mark.parametrize("k", [1, 4])
def test_pipeline_matches_per_step_reference(k):
    """Full windows: K steps per dispatch == K jitted-per-step calls,
    exactly, with ONE compile for the hot loop."""
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O0")
    batches = _batches(8)
    ref_state, ref_losses = _reference(step_fn, init_fn(_params()), batches)

    pipe = runtime.StepPipeline(step_fn, k=k)
    state = init_fn(_params())
    with assert_trace_count(pipe.loop, 1):
        state, reader = pipe.run(
            state, runtime.window_batches(iter(batches), k))
    assert reader.steps_pushed == len(batches)
    _assert_states_match(state, ref_state)
    # the LAST window's per-step losses match the reference tail
    last = np.ravel(reader.last()["loss"])
    np.testing.assert_allclose(last[:k], ref_losses[-k:], rtol=1e-5)


@pytest.mark.parametrize("k", [1, 4])
def test_ragged_tail_no_retrace(k):
    """An epoch tail shorter than K pads to the same [K, ...] shape and
    runs through the (separately compiled, select-gated) tail program —
    one compile each, and the padded steps must not advance the state."""
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O0")
    n = 2 * k + max(1, k - 1)          # two full windows + a ragged tail
    batches = _batches(n)
    ref_state, _ = _reference(step_fn, init_fn(_params()), batches)

    pipe = runtime.StepPipeline(step_fn, k=k)
    state = init_fn(_params())
    with assert_trace_count(pipe.loop, 1), \
            assert_trace_count(pipe.tail_loop, 1 if k > 1 else 0):
        state, reader = pipe.run(
            state, runtime.window_batches(iter(batches), k))
    assert reader.steps_pushed == n
    _assert_states_match(state, ref_state)


@pytest.mark.parametrize("k", [1, 4])
def test_overflow_skip_mid_window(k):
    """Dynamic loss scaling: an overflow in the middle of a window must
    skip that step's update ON DEVICE (no retrace, no host sync) and
    land on the same params and loss scale as the per-step path."""
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O2", loss_scale="dynamic")
    batches = _batches(2 * k + 1, bad_step=k // 2)   # mid-first-window
    ref_state, _ = _reference(step_fn, init_fn(_params()), batches)
    assert float(ref_state.scaler.loss_scale) < 2.0 ** 16  # it DID overflow

    pipe = runtime.StepPipeline(step_fn, k=k)
    state = init_fn(_params())
    with assert_trace_count(pipe.loop, 1):
        state, reader = pipe.run(
            state, runtime.window_batches(iter(batches), k))
    _assert_states_match(state, ref_state)
    assert float(state.scaler.loss_scale) == \
        float(ref_state.scaler.loss_scale)
    # per-step overflow flags came back as a stacked [K] device array
    flags = np.ravel(reader.last()["overflow"])
    assert flags.shape[0] == k


def test_deferred_metrics_one_dispatch_behind():
    reader = runtime.DeferredMetrics()
    assert reader.push({"loss": jnp.float32(0.0)}, 4) is None
    prev = reader.push({"loss": jnp.float32(1.0)}, 4)
    assert prev is not None and prev.step == 0 and prev.n_valid == 4
    assert reader.newest().step == 4
    assert reader.steps_pushed == 8
    host = reader.last()               # newest window, host values
    np.testing.assert_allclose(host["loss"], 1.0)


@pytest.mark.parametrize("n_windows", [1, 2, 5])
def test_deferred_metrics_flush_drops_no_window(n_windows):
    """ISSUE 5 satellite regression: every pushed window is handed back
    exactly once across push() returns + one flush() — the final
    in-flight window (which push alone never returns) is not silently
    dropped at loop exit."""
    reader = runtime.DeferredMetrics()
    returned = []
    for i in range(n_windows):
        prev = reader.push({"loss": jnp.float32(i)}, 4)
        if prev is not None:
            returned.append(prev.step)
    flushed = reader.flush()
    returned += [wm.step for wm in flushed]
    assert returned == [4 * i for i in range(n_windows)]
    assert reader.flush() == []        # idempotent until the next push
    # the flushed handles fetch like any other window
    np.testing.assert_allclose(flushed[-1].fetch()["loss"],
                               n_windows - 1)


def test_deferred_metrics_flush_empty_and_run_drains():
    assert runtime.DeferredMetrics().flush() == []
    # StepPipeline.run drains through flush: on_metrics sees EVERY window
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O0")
    pipe = runtime.StepPipeline(step_fn, k=2)
    seen = []
    pipe.run(init_fn(_params()),
             runtime.window_batches(iter(_batches(6)), 2),
             on_metrics=lambda wm: seen.append(wm.step))
    assert seen == [0, 2, 4]


def test_window_batches_pad_and_drop():
    batches = [(np.full((2,), i, np.float32),) for i in range(5)]
    padded = list(runtime.window_batches(iter(batches), 2))
    assert [n for _, n in padded] == [2, 2, 1]
    # the pad repeats the last real batch to keep shapes static
    last_window = padded[-1][0][0]
    assert last_window.shape == (2, 2)
    np.testing.assert_array_equal(last_window[0], last_window[1])
    dropped = list(runtime.window_batches(iter(batches), 2, pad_tail=False))
    assert [n for _, n in dropped] == [2, 2]


def test_stage_windows_yields_device_arrays():
    """stage_windows = window_batches staged through PrefetchLoader: the
    yielded windows are already device arrays (the H2D happened on the
    producer thread), n_valid passes through as a plain int."""
    batches = [(np.full((2, 3), i, np.float32),) for i in range(5)]
    out = list(runtime.stage_windows(iter(batches), 2))
    assert [n for _, n in out] == [2, 2, 1]
    leaf = out[0][0][0]
    assert isinstance(leaf, jax.Array)
    assert leaf.shape == (2, 2, 3)
    np.testing.assert_array_equal(np.asarray(out[1][0][0][0]),
                                  np.full((2, 3), 2, np.float32))


def test_chain_steps_donates_window_buffer():
    """donate_argnums=(0, 1) must release the stacked batch window.  On
    the CPU backend a donated input is only deleted when XLA can alias
    it onto an output, so the probe step echoes a window-shaped metrics
    leaf; on TPU jaxlibs the window is an XLA buffer donor regardless."""
    def echo_step(state, batch):
        (x,) = batch
        return state + jnp.sum(x), {"echo": x}

    chained = jax.jit(chain_steps(echo_step), donate_argnums=(0, 1))
    state = jnp.float32(0.0)
    window = (jnp.ones((4, 8), jnp.float32),)
    new_state, metrics = chained(state, window)
    jax.block_until_ready(metrics["echo"])
    assert window[0].is_deleted(), \
        "stacked window survived donate_argnums=(0, 1)"
    assert float(new_state) == 32.0


def test_step_pipeline_donate_window_flag():
    """donate_window=True consumes streamed windows; donate_window=False
    keeps a reused pool window alive across calls (the synthetic-data
    shape the examples use)."""
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O0")
    window, n = next(runtime.window_batches(iter(_batches(4)), 4))
    window = jax.device_put(window)

    pipe = runtime.StepPipeline(step_fn, k=4, donate_window=False)
    state = init_fn(_params())
    for _ in range(3):                       # reuse MUST be safe
        state, metrics = pipe.step_window(state, window, n)
    assert not any(getattr(l, "is_deleted", lambda: False)()
                   for l in jax.tree_util.tree_leaves(window))
    float(np.ravel(jax.device_get(metrics["loss"]))[-1])


def test_pipeline_rejects_bad_k_and_n_valid():
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.1),
                                       opt_level="O0")
    with pytest.raises(ValueError):
        runtime.StepPipeline(step_fn, k=0)
    pipe = runtime.StepPipeline(step_fn, k=2)
    window, _ = next(runtime.window_batches(iter(_batches(2)), 2))
    with pytest.raises(ValueError):
        pipe.step_window(init_fn(_params()), window, n_valid=0)


# -- GracefulShutdown (ISSUE 9) -----------------------------------------------

def test_graceful_shutdown_signal_sets_drain_flag():
    """A real SIGTERM delivered to this process flips the drain flag
    (the window-boundary poll the examples check) without raising; the
    previous handler comes back on uninstall."""
    import os as _os
    import signal as _sig

    prev = _sig.getsignal(_sig.SIGTERM)
    with runtime.GracefulShutdown(signals=(_sig.SIGTERM,)) as stop:
        assert not stop.draining
        _os.kill(_os.getpid(), _sig.SIGTERM)
        # the handler runs on the main thread at the next bytecode
        # boundary; the event wait gives it that chance portably
        assert stop._drain.wait(timeout=5)
        assert stop.draining
        assert stop.reason == "signal:SIGTERM"
    assert _sig.getsignal(_sig.SIGTERM) is prev


def test_graceful_shutdown_request_emits_drain_event(tmp_path):
    import json

    from apex_tpu import telemetry

    rec = telemetry.start(str(tmp_path / "run.jsonl"))
    try:
        stop = runtime.GracefulShutdown()
        stop.request("preemption-notice")
        stop.request("second-call-is-idempotent")
    finally:
        rec.close()
        telemetry.set_recorder(None)
    events = [json.loads(line) for line in
              open(str(tmp_path / "run.jsonl")) if line.strip()]
    drains = [e for e in events if e["kind"] == "drain"]
    assert len(drains) == 1                       # first request only
    assert drains[0]["reason"] == "preemption-notice"
    assert stop.draining and stop.reason == "preemption-notice"
