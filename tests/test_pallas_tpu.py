"""On-chip Pallas kernel tests (VERDICT r1 weak-#3: the CPU-pinned suite
only ever exercised the jnp fallbacks).

Run with ``APEX_TPU_TESTS=1 python -m pytest tests/ -m tpu`` on a TPU host:
the ``tpu``-marked tests below execute the Mosaic kernels directly and
compare them against the jnp oracle paths — the fallback-vs-kernel testing
strategy of reference ``tests/L0/run_fused_layer_norm`` and
``apex/contrib/test/test_label_smoothing.py:10-28``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _tpu_dev():
    return jax.devices("tpu")[0]


# -- FusedLayerNorm kernels ---------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape", [(8, 128), (300, 768), (257, 1024)])
def test_layer_norm_pallas_fwd_matches_oracle(dtype, shape):
    from apex_tpu.normalization.fused_layer_norm import _fwd_ref, _pallas_fwd

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), dtype)
    w = jnp.asarray(rng.rand(shape[1]) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(shape[1]), jnp.float32)

    with jax.default_device(_tpu_dev()):
        out_k, mean_k, invvar_k = jax.jit(
            lambda x, w, b: _pallas_fwd(x, w, b, 1e-5))(x, w, b)
    out_r, mean_r, invvar_r = _fwd_ref(x, w, b, 1e-5)

    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(np.asarray(out_k, np.float32),
                               np.asarray(out_r, np.float32),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(mean_k), np.asarray(mean_r),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(invvar_k), np.asarray(invvar_r),
                               atol=1e-3, rtol=1e-3)


def test_layer_norm_pallas_bwd_matches_oracle():
    from apex_tpu.normalization.fused_layer_norm import (
        _bwd_input_ref, _fwd_ref, _pallas_bwd_input)

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64, 512), jnp.float32)
    w = jnp.asarray(rng.rand(512) + 0.5, jnp.float32)
    g = jnp.asarray(rng.randn(64, 512), jnp.float32)
    _, mean, invvar = _fwd_ref(x, w, None, 1e-5)

    with jax.default_device(_tpu_dev()):
        dx_k = jax.jit(lambda g, x, m, iv, w:
                       _pallas_bwd_input(g, x, m, iv, w))(g, x, mean,
                                                          invvar, w)
    dx_r = _bwd_input_ref(g, x, mean, invvar, w)
    np.testing.assert_allclose(np.asarray(dx_k), np.asarray(dx_r),
                               atol=1e-4, rtol=1e-4)


def test_layer_norm_end_to_end_grad_on_chip():
    """Full custom-VJP path under jit on the TPU default device."""
    from apex_tpu.normalization.fused_layer_norm import (_use_pallas,
                                                         fused_layer_norm)

    with jax.default_device(_tpu_dev()):
        assert _use_pallas(), "pallas path must be active on chip"
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(32, 256), jnp.float32)
        w = jnp.ones((256,), jnp.float32)
        b = jnp.zeros((256,), jnp.float32)

        # impl="pallas": (32, 256) is below the auto-dispatch crossover,
        # and THIS test exists to exercise the kernel VJP on chip.
        def loss(x, w, b):
            return jnp.sum(fused_layer_norm(x, 256, w, b, impl="pallas") ** 2)

        gx, gw, gb = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, b)

    import os
    os.environ["APEX_TPU_DISABLE_PALLAS"] = "1"
    try:
        gx_r, gw_r, gb_r = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, b)
    finally:
        del os.environ["APEX_TPU_DISABLE_PALLAS"]
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r),
                               atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r),
                               atol=1e-2, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_r),
                               atol=1e-2, rtol=1e-3)


# -- xentropy kernels ---------------------------------------------------------

@pytest.mark.parametrize("shape", [(128, 512), (2048, 30522)])
def test_xentropy_pallas_fwd_matches_oracle(shape):
    """Includes the LM-vocab shape that OOM'd VMEM before row-block sizing."""
    from apex_tpu.contrib.xentropy import _fwd_pallas, _fwd_ref

    n, h = shape
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(n, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, h, (n,)), jnp.int32)

    with jax.default_device(_tpu_dev()):
        loss_k, mlse_k = jax.jit(
            lambda l, y: _fwd_pallas(l, y, 0.1))(logits, labels)
    loss_r, mlse_r = _fwd_ref(logits, labels, 0.1)
    np.testing.assert_allclose(np.asarray(loss_k), np.asarray(loss_r),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(mlse_k), np.asarray(mlse_r),
                               atol=1e-4, rtol=1e-4)


def test_xentropy_pallas_bwd_matches_oracle():
    from apex_tpu.contrib.xentropy import _bwd_pallas, _bwd_ref, _fwd_ref

    rng = np.random.RandomState(4)
    logits = jnp.asarray(rng.randn(256, 1000), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (256,)), jnp.int32)
    g = jnp.asarray(rng.rand(256), jnp.float32)
    _, mlse = _fwd_ref(logits, labels, 0.1)

    with jax.default_device(_tpu_dev()):
        dx_k = jax.jit(lambda g, l, m, y:
                       _bwd_pallas(g, l, m, y, 0.1))(g, logits, mlse, labels)
    dx_r = _bwd_ref(g, logits, mlse, labels, 0.1)
    np.testing.assert_allclose(np.asarray(dx_k), np.asarray(dx_r),
                               atol=1e-5, rtol=1e-4)


def test_xentropy_end_to_end_grad_on_chip():
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    with jax.default_device(_tpu_dev()):
        rng = np.random.RandomState(5)
        logits = jnp.asarray(rng.randn(64, 128), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 128, (64,)), jnp.int32)
        labels = labels.at[0].set(0)   # exercise padding_idx masking

        def loss(l):
            return jnp.sum(softmax_cross_entropy_loss(l, labels,
                                                      smoothing=0.1,
                                                      padding_idx=0))
        val_k = jax.jit(loss)(logits)
        grad_k = jax.jit(jax.grad(loss))(logits)

    import os
    os.environ["APEX_TPU_DISABLE_PALLAS"] = "1"
    try:
        val_r = jax.jit(loss)(logits)
        grad_r = jax.jit(jax.grad(loss))(logits)
    finally:
        del os.environ["APEX_TPU_DISABLE_PALLAS"]
    np.testing.assert_allclose(float(val_k), float(val_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_k), np.asarray(grad_r),
                               atol=1e-5, rtol=1e-4)
    # padded row contributes zero gradient
    assert np.allclose(np.asarray(grad_k)[0], 0.0)

# -- flash attention kernels --------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_attention_fwd_on_chip(causal, dtype):
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(6)
    B, T, H, D = 2, 512, 4, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), dtype) for _ in range(3))

    with jax.default_device(_tpu_dev()):
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=256, block_k=256))(q, k, v)
    ref = dot_product_attention(q.astype(jnp.float32),
                                k.astype(jnp.float32),
                                v.astype(jnp.float32), causal=causal)
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-4
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=tol, rtol=tol)


def test_flash_attention_bias_on_chip():
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(7)
    B, T, H, D = 2, 384, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    valid = jnp.arange(T)[None, :] < jnp.array([300, 128])[:, None]
    kb = jnp.where(valid, 0.0, -1e9)

    with jax.default_device(_tpu_dev()):
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, key_padding_bias=kb, block_q=128, block_k=128))(q, k, v)
    ref = dot_product_attention(q, k, v, bias=kb[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_on_chip(causal):
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(8)
    B, T, H, D = 1, 256, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    with jax.default_device(_tpu_dev()):
        g_k = jax.jit(jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128)),
            argnums=(0, 1, 2)))(q, k, v)
    g_r = jax.grad(loss(lambda q, k, v: dot_product_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_k, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


def test_flash_attention_dynamic_offsets_on_chip():
    """The ring-attention hook: causal masking on GLOBAL positions via the
    dynamic q_offset/k_offset SMEM scalars, compiled on chip."""
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import _flash_fwd_pallas

    rng = np.random.RandomState(10)
    B, T, H, D = 1, 128, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
               for _ in range(3))

    with jax.default_device(_tpu_dev()):
        # q rows at global positions 128..255, k at 0..127 -> fully visible
        out_past, _ = jax.jit(lambda q, k, v: _flash_fwd_pallas(
            q, k, v, None, sm_scale=D ** -0.5, causal=True,
            block_q=128, block_k=128, q_offset=128, k_offset=0))(q, k, v)
        # diagonal shard: plain causal
        out_diag, _ = jax.jit(lambda q, k, v: _flash_fwd_pallas(
            q, k, v, None, sm_scale=D ** -0.5, causal=True,
            block_q=128, block_k=128, q_offset=0, k_offset=0))(q, k, v)
        # future shard: fully masked -> zeros, lse = NEG_INF
        out_fut, lse_fut = jax.jit(lambda q, k, v: _flash_fwd_pallas(
            q, k, v, None, sm_scale=D ** -0.5, causal=True,
            block_q=128, block_k=128, q_offset=0, k_offset=128))(q, k, v)

    qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    ref_past = dot_product_attention(qs, ks, vs).transpose(0, 2, 1, 3)
    ref_diag = dot_product_attention(qs, ks, vs,
                                     causal=True).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out_past), np.asarray(ref_past),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(out_diag), np.asarray(ref_diag),
                               atol=2e-4, rtol=2e-4)
    assert np.allclose(np.asarray(out_fut), 0.0)
    assert np.all(np.asarray(lse_fut) <= -1e29)


def test_flash_attention_sublane_only_shape_on_chip():
    """T=136 (17x8, not a 128-multiple): whole-array blocks equal to the
    array dims — the Mosaic edge _pick_block's sublane rule permits."""
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(11)
    B, T, H, D = 1, 136, 1, 32
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    with jax.default_device(_tpu_dev()):
        # explicit blocks force the KERNEL (the r5 shape dispatch would
        # otherwise route this sub-crossover shape to the jnp path);
        # whole-array 136-blocks still exercise the sublane rule.
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=136, block_k=136))(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_ring_flash_kernel_under_default_vma_on_chip():
    """The ring-flash Mosaic kernel path must trace and run under
    shard_map's DEFAULT vma tracking (VERDICT r2 next #4): the kernels
    pcast-align their rank-varying offset operands (pallas_compat.
    align_vma), so no check_vma=False escape hatch is needed.  The jnp
    fallback is monkeypatched to fail loudly, proving the kernel ran."""
    import sys

    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from apex_tpu.ops.attention import blockwise_attention

    import apex_tpu.parallel.ring_attention  # noqa: F401  (registers module)
    ra = sys.modules["apex_tpu.parallel.ring_attention"]

    rng = np.random.RandomState(3)
    B, T, H, D = 2, 1024, 4, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.bfloat16)
               for _ in range(3))

    def _no_fallback(*a, **k):
        raise AssertionError("ring_flash fell back to the jnp ring under "
                             "default vma tracking")

    orig = ra.ring_attention
    ra.ring_attention = _no_fallback
    try:
        mesh = Mesh(np.array(jax.devices("tpu")[:1]), ("sp",))
        f = shard_map(
            lambda q, k, v: ra.ring_flash_attention(q, k, v, "sp",
                                                    causal=True),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"))          # default check_vma=True
        out = jax.jit(f)(q, k, v)
        ref = blockwise_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=8e-3, rtol=8e-3)

        g = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(f(a, b, c).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(
                blockwise_attention(a, b, c,
                                    causal=True).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=0.1, rtol=0.1)
    finally:
        ra.ring_attention = orig


def test_flash_2d_bias_kernels_on_chip():
    """Mosaic: [B,T,S] head-broadcast bias fwd + grads vs oracle — incl.
    the db2 kernel's head-innermost resident accumulation, which interpret
    mode cannot validate (revisited output blocks only stay resident on
    real Pallas TPU grids)."""
    from apex_tpu.ops.attention import blockwise_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    B, T, H, D = 2, 512, 4, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D) * .5, jnp.bfloat16)
               for _ in range(3))
    seg = jnp.asarray(rng.randint(0, 3, (B, T)))
    bias = jnp.where(seg[:, :, None] == seg[:, None, :], 0.0,
                     -1e30).astype(jnp.float32)

    for causal in (False, True):
        f = lambda q, k, v, bias: flash_attention(
            q, k, v, causal=causal, bias=bias, block_q=128, block_k=128)
        ref = lambda q, k, v, bias: blockwise_attention(
            q, k, v, causal=causal, bias=bias[:, None])
        with jax.default_device(_tpu_dev()):
            out = jax.jit(f)(q, k, v, bias)
            g = jax.jit(jax.grad(
                lambda *a: jnp.sum(f(*a).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2, 3)))(q, k, v, bias)
        r = ref(q, k, v, bias)
        gr = jax.jit(jax.grad(
            lambda *a: jnp.sum(ref(*a).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2, 3)))(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(r, np.float32),
                                   atol=5e-3, rtol=5e-3)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=0.08, rtol=0.08)


def test_tp_self_attention_flash_kernel_on_chip():
    """dp x tp style head-parallel attention on a 1-device tp mesh under
    DEFAULT shard_map: the DEFAULT attention_fn must run the Mosaic flash
    kernel (jnp fallback forbidden) and match the dense reference.  T is
    above the r5 shape-dispatch crossover so the default path really is
    the kernel path here; the dispatch itself (sub-crossover shapes
    routing to jnp) is covered by test_flash_dispatch_* in
    tests/test_flash_attention.py."""
    # NOTE: `import apex_tpu.ops.flash_attention as fa` binds the
    # FUNCTION re-exported by ops/__init__ (it shadows the submodule
    # attribute) — import the symbol directly instead.
    from apex_tpu.ops.flash_attention import _KERNEL_MIN_KV
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.parallel.tensor_parallel import tp_self_attention

    rng = np.random.RandomState(5)
    B, T, d, H, hd = 2, max(1024, _KERNEL_MIN_KV), 64, 4, 32
    x = jnp.asarray(rng.randn(B, T, d) * .5, jnp.float32)
    wqkv = jnp.asarray(rng.randn(d, 3, H, hd) * .2, jnp.float32)
    wo = jnp.asarray(rng.randn(H * hd, d) * .2, jnp.float32)

    import apex_tpu.ops.attention as att
    orig = att.blockwise_attention

    def _no_fallback(*a, **k):
        raise AssertionError("tp flash attention fell back to jnp")

    att.blockwise_attention = _no_fallback
    try:
        mesh = Mesh(np.array(jax.devices("tpu")[:1]), ("tp",))
        f = shard_map(
            lambda x, wq, wo: tp_self_attention(x, wq, wo, H, "tp",
                                                causal=True),
            mesh=mesh, in_specs=(P(), P(None, None, "tp"), P("tp")),
            out_specs=P())
        out = jax.jit(f)(x, wqkv, wo)
    finally:
        att.blockwise_attention = orig

    qkv = jnp.einsum("btd,dche->btche", x, wqkv)
    ctx = dot_product_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                                causal=True)
    ref = ctx.reshape(B, T, -1) @ wo
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=5e-3)


def test_flash_gqa_kernels_on_chip():
    """Mosaic GQA: the 5-D dkv grid's resident dk/dv accumulation across
    the group-member dim is TPU-specific — interpret mode cannot validate
    it.  With key-padding bias so the per-q-head db path is exercised
    under grouping too."""
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    B, T, H, HKV, D = 2, 512, 8, 2, 64
    q = jnp.asarray(rng.randn(B, T, H, D) * .5, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, T, HKV, D) * .5, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, T, HKV, D) * .5, jnp.bfloat16)
    kb = jnp.where(jnp.arange(T)[None, :] < 400, 0.0,
                   -1e9) * jnp.ones((B, 1))

    def ref(q, k, v, causal):
        kr = jnp.repeat(k, H // HKV, axis=2)
        vr = jnp.repeat(v, H // HKV, axis=2)
        return dot_product_attention(q, kr, vr, causal=causal,
                                     bias=kb[:, None, None, :])

    for causal in (False, True):
        f = lambda q, k, v: flash_attention(
            q, k, v, causal=causal, key_padding_bias=kb,
            block_q=128, block_k=128)
        with jax.default_device(_tpu_dev()):
            out = jax.jit(f)(q, k, v)
            g = jax.jit(jax.grad(
                lambda *a: jnp.sum(f(*a).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))(q, k, v)
        r = ref(q, k, v, causal)
        gr = jax.jit(jax.grad(
            lambda *a: jnp.sum(ref(*a, causal).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(r, np.float32),
                                   atol=1e-2, rtol=1e-2)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=0.2, rtol=0.1)


def test_flash_sliding_window_on_chip():
    """Mosaic: bounded sliding-window grid (virtual-negative KV blocks
    clamped in the index maps, dead steps predicated off) vs the band-bias
    oracle — fwd + grads."""
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import NEG_INF, flash_attention

    rng = np.random.RandomState(0)
    B, T, H, D, W = 1, 2048, 4, 64, 256
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D) * .5, jnp.bfloat16)
               for _ in range(3))
    band = jnp.where(
        (jnp.arange(T)[:, None] - jnp.arange(T)[None, :]) < W, 0.0, NEG_INF)

    f = lambda q, k, v: flash_attention(q, k, v, causal=True, window=W,
                                        block_q=128, block_k=128)
    ref = lambda q, k, v: dot_product_attention(q, k, v, causal=True,
                                                bias=band[None, None])
    with jax.default_device(_tpu_dev()):
        out = jax.jit(f)(q, k, v)
        g = jax.jit(jax.grad(
            lambda *a: jnp.sum(f(*a).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
    r = ref(q, k, v)
    gr = jax.jit(jax.grad(
        lambda *a: jnp.sum(ref(*a).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(r, np.float32),
                               atol=1e-2, rtol=1e-2)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=0.1, rtol=0.1)


def test_layer_norm_dispatch_structural():
    """The r5 auto dispatch is visible in the lowering: below the
    in-context crossover the jitted program contains NO layer-norm
    custom call (pure XLA fusion); at/above it, exactly the kernel.
    Lowering only — no compile, so this stays cheap on chip."""
    from apex_tpu.normalization.fused_layer_norm import fused_layer_norm

    with jax.default_device(_tpu_dev()):
        f = jax.jit(lambda x: fused_layer_norm(x, 768))
        small = f.lower(
            jax.ShapeDtypeStruct((2048, 768), jnp.bfloat16)).as_text()
        assert "tpu_custom_call" not in small
        big = f.lower(
            jax.ShapeDtypeStruct((8192, 768), jnp.bfloat16)).as_text()
        assert "tpu_custom_call" in big
