"""Install-matrix gate — the ``tests/docker_extension_builds`` analog.

The reference CI installs apex across ~7 images and asserts the
Python-only tier stays fully functional (SURVEY.md §1: "A Python-only
build must remain fully functional for amp, DDP, and SyncBatchNorm").
The TPU build's tiers are: native C++ runtime (ctypes .so) vs numpy
fallback, and Pallas kernels vs jnp fallback.  Each test forces the
degraded tier and asserts behavior matches the full tier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import native


def _tiers(monkeypatch):
    """Force the python fallback tier in apex_tpu.native."""
    monkeypatch.setattr(native, "_lib", False)
    monkeypatch.setattr(native, "available", False)


def test_flatten_unflatten_python_tier_matches_native(monkeypatch):
    rng = np.random.RandomState(0)
    arrays = [rng.randn(3, 4).astype(np.float32),
              rng.randint(0, 9, (7,)).astype(np.int64),
              rng.randn(2, 2, 2).astype(np.float16)]
    flat_native = native.flatten(arrays)
    back_native = native.unflatten(flat_native, arrays)

    _tiers(monkeypatch)
    flat_py = native.flatten(arrays)
    back_py = native.unflatten(flat_py, arrays)

    np.testing.assert_array_equal(flat_native, flat_py)
    for a, b, orig in zip(back_native, back_py, arrays):
        np.testing.assert_array_equal(a, orig)
        np.testing.assert_array_equal(b, orig)


def test_u8_decode_python_tier_matches_native(monkeypatch):
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (4, 8, 8, 3), dtype=np.uint8)
    mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
    full = native.u8_to_f32_nhwc(imgs, mean, std)
    _tiers(monkeypatch)
    fallback = native.u8_to_f32_nhwc(imgs, mean, std)
    np.testing.assert_allclose(full, fallback, atol=1e-6)


def test_synth_bytes_python_tier_bit_identical(monkeypatch):
    """The counter-based synthetic generator must be BIT-identical
    across tiers (both walk the same splitmix64 lattice): synthetic
    benchmark inputs cannot depend on whether g++ was present."""
    full = native.synth_bytes(4099, seed=123)        # ragged tail too
    _tiers(monkeypatch)
    fallback = native.synth_bytes(4099, seed=123)
    np.testing.assert_array_equal(full, fallback)


def test_stale_mtime_without_compiler_loads_existing_so(monkeypatch):
    """Review fix: a prebuilt .so whose mtime lies (git doesn't preserve
    mtimes) on a box without g++ must still load — the ABI-version check
    judges the build, not the filesystem timestamp."""
    import os

    native._load()
    if not native.available:
        pytest.skip("native tier unavailable in this environment")
    src = os.path.join(native._CSRC, "apex_runtime.cpp")
    so_times = (os.path.getatime(native._SO), os.path.getmtime(native._SO))
    # make the .so look older than the source, and the compiler vanish
    os.utime(native._SO, (so_times[0], os.path.getmtime(src) - 10))
    monkeypatch.setattr(native, "_build", lambda: None)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "available", False)
    try:
        assert native._load() is not None
        assert native.available
    finally:
        os.utime(native._SO, so_times)


def test_crop_flip_normalize_python_tier_matches_native(monkeypatch):
    """The fused augmentation epilogue: numpy tier == C++ tier for the
    same caller-provided offsets/flips (randomness lives in the caller,
    so the tiers are directly comparable)."""
    rng = np.random.RandomState(5)
    imgs = rng.randint(0, 256, (3, 10, 11, 3), dtype=np.uint8)
    offsets = np.array([[0, 0], [2, 3], [1, 1]], np.int32)
    flips = np.array([1, 0, 1], np.uint8)
    mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
    full = native.crop_flip_normalize(imgs, 8, offsets, flips, mean, std)
    _tiers(monkeypatch)
    fallback = native.crop_flip_normalize(imgs, 8, offsets, flips,
                                          mean, std)
    np.testing.assert_allclose(full, fallback, atol=1e-6)


@pytest.mark.slow
def test_pallas_disabled_tier_full_train_step(monkeypatch):
    """APEX_TPU_DISABLE_PALLAS=1: FusedLayerNorm + xentropy + flash all
    take the jnp tier and an O2 train step still runs and learns."""
    monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")

    from apex_tpu import training
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models import bert_tiny
    from apex_tpu.training import make_train_step

    model = bert_tiny(num_classes=None, dtype=jnp.bfloat16,
                      attention_impl="flash")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 1024, (4, 32)))
    labels = jnp.asarray(rng.randint(0, 1024, (4, 32)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, b):
        ids_b, y = b
        feats = model.apply({"params": p}, ids_b)
        logits = feats @ p["word_embeddings"]["embedding"].T
        return jnp.mean(softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), y.reshape(-1),
            smoothing=0.1))

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-3),
                                       opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn)
    losses = []
    for _ in range(4):
        state, m = step(state, (ids, labels))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.all(np.isfinite(losses))


def test_flash_attention_without_pltpu(monkeypatch):
    """A build where pallas TPU support is absent entirely (pltpu=None)
    must silently take the jnp blockwise path with identical semantics."""
    import importlib
    # The function re-export in apex_tpu.ops shadows the submodule name.
    fa = importlib.import_module("apex_tpu.ops.flash_attention")

    monkeypatch.setattr(fa, "pltpu", None)
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 128, 2, 16), jnp.float32)
    out = fa.flash_attention(q, q, q, causal=True)
    from apex_tpu.ops.attention import dot_product_attention
    ref = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multi_tensor_reports_tier():
    """``multi_tensor_applier.available`` analog: the tier flag exists and
    is truthful (reference multi_tensor_apply.py:3-30 two-tier check)."""
    from apex_tpu import multi_tensor
    assert hasattr(multi_tensor, "MultiTensorApply")
    assert isinstance(native.available, bool)
