"""Fused conv-side BN epilogue tests (ISSUE 7): bn_relu_residual kernel
parity (interpret mode vs the jnp reference), custom-VJP exactness
through full-BN autodiff, the SyncBatchNorm tail routing, and the
ResNet norm-factory hook's fused-vs-explicit block equivalence.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization.fused_bn_act import (_dispatch_pallas,
                                                 _kernel_fits,
                                                 bn_act_epilogue_ref,
                                                 bn_relu_residual)


def _operands(c=8, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(2, 5, 5, c), dtype)
    z = jnp.asarray(rng.randn(2, 5, 5, c), dtype)
    mean = jnp.asarray(rng.randn(c), jnp.float32)
    invstd = jnp.asarray(np.abs(rng.randn(c)) + 0.3, jnp.float32)
    w = jnp.asarray(rng.randn(c), jnp.float32)
    b = jnp.asarray(rng.randn(c), jnp.float32)
    return x, z, mean, invstd, w, b


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("with_z", [True, False])
@pytest.mark.parametrize("affine", [True, False])
def test_kernel_interpret_forward_parity(dtype, relu, with_z, affine):
    x, z, mean, invstd, w, b = _operands(dtype=dtype)
    zz = z if with_z else None
    ww, bb = (w, b) if affine else (None, None)
    got = bn_relu_residual(x, mean, invstd, ww, bb, z=zz, relu=relu,
                           interpret=True)
    want = bn_act_epilogue_ref(x, mean, invstd, ww, bb, z=zz, relu=relu)
    assert got.dtype == x.dtype
    tol = 1e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=tol)


def test_kernel_interpret_gradient_parity_all_inputs():
    x, z, mean, invstd, w, b = _operands(seed=1)

    def loss(interp, xx, mm, ii, ww, bb, zz):
        return jnp.sum(bn_relu_residual(xx, mm, ii, ww, bb, z=zz,
                                        relu=True, interpret=interp) ** 2)

    g_k = jax.grad(functools.partial(loss, True),
                   argnums=(0, 1, 2, 3, 4, 5))(x, mean, invstd, w, b, z)
    g_r = jax.grad(functools.partial(loss, False),
                   argnums=(0, 1, 2, 3, 4, 5))(x, mean, invstd, w, b, z)
    for a, r in zip(g_k, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-4, rtol=1e-4)


def test_custom_vjp_exact_through_full_bn():
    """mean/invstd are differentiable inputs whose cotangents flow back
    into the XLA-side statistics — full-BN autodiff through the fused
    epilogue must equal plain-jnp composition autodiff."""
    x, z, _, _, w, b = _operands(seed=2)

    def full(use, xx, ww, bb, zz):
        xf = xx.astype(jnp.float32)
        m = xf.mean((0, 1, 2))
        inv = jax.lax.rsqrt(xf.var((0, 1, 2)) + 1e-5)
        if use:
            y = bn_relu_residual(xx, m, inv, ww, bb, z=zz, relu=True)
        else:
            y = jax.nn.relu((xf - m) * inv * ww + bb
                            + zz.astype(jnp.float32)).astype(xx.dtype)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    g_f = jax.grad(functools.partial(full, True),
                   argnums=(0, 1, 2, 3))(x, w, b, z)
    g_r = jax.grad(functools.partial(full, False),
                   argnums=(0, 1, 2, 3))(x, w, b, z)
    for a, r in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-4, rtol=1e-4)


def test_sync_batchnorm_tail_routes_through_epilogue():
    """SyncBatchNorm(channel_last=True) output is the epilogue applied
    to its own computed moments — op-identical (bitwise on CPU jnp)."""
    from apex_tpu.parallel import SyncBatchNorm

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 6, 6, 5), jnp.float32)
    z = jnp.asarray(rng.randn(4, 6, 6, 5), jnp.float32)
    model = SyncBatchNorm(num_features=5, fuse_relu=True)
    variables = model.init(jax.random.PRNGKey(0), x, z)
    y, _ = model.apply(variables, x, z, mutable=["batch_stats"])
    xf = np.asarray(x).reshape(-1, 5)
    mean, var = xf.mean(0), xf.var(0)
    invstd = 1.0 / np.sqrt(var + 1e-5)
    want = bn_act_epilogue_ref(x, jnp.asarray(mean), jnp.asarray(invstd),
                               jnp.ones((5,)), jnp.zeros((5,)), z=z,
                               relu=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), atol=1e-5)


def test_dispatch_gates():
    """Off-TPU the dispatch always takes jnp; the width gate keeps
    blocks whose 8-row floor exceeds scoped VMEM off the kernel."""
    assert not _dispatch_pallas(10 ** 6, 256, None, 4)   # no TPU backend
    with pytest.raises(ValueError, match="impl"):
        _dispatch_pallas(8, 8, "mosaic", 4)
    assert _kernel_fits(256, 4)
    assert not _kernel_fits(10 ** 6, 4)                  # 8-row floor OOM


def _tiny_resnet(fused_epilogue):
    from apex_tpu.models import ResNet18
    return ResNet18(num_classes=10, dtype=jnp.float32, sync_bn=True,
                    fused_epilogue=fused_epilogue)


def test_resnet_norm_factory_fused_matches_explicit():
    """The block rewiring is routing, not math: a SyncBatchNorm ResNet
    with the fused chains must match the explicit relu/add statements
    on the SAME parameters — forward and grads."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    m_fused, m_plain = _tiny_resnet(None), _tiny_resnet(False)
    variables = m_fused.init(jax.random.PRNGKey(0), x, train=True)
    # identical param/stat trees: the hook changes no module names
    v2 = m_plain.init(jax.random.PRNGKey(0), x, train=True)
    assert (jax.tree_util.tree_structure(variables)
            == jax.tree_util.tree_structure(v2))

    def fwd(model, p):
        y, upd = model.apply({"params": p,
                              "batch_stats": variables["batch_stats"]},
                             x, train=True, mutable=["batch_stats"])
        return jnp.sum(y ** 2), upd

    (y_f, upd_f), g_f = jax.value_and_grad(
        lambda p: fwd(m_fused, p), has_aux=True)(variables["params"])
    (y_p, upd_p), g_p = jax.value_and_grad(
        lambda p: fwd(m_plain, p), has_aux=True)(variables["params"])
    np.testing.assert_allclose(float(y_f), float(y_p), rtol=1e-6)
    for a, r in zip(jax.tree_util.tree_leaves(g_f),
                    jax.tree_util.tree_leaves(g_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-5, rtol=1e-4)
    for a, r in zip(jax.tree_util.tree_leaves(upd_f),
                    jax.tree_util.tree_leaves(upd_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-5, rtol=1e-4)


def test_resnet_fused_epilogue_requires_capable_norm():
    from apex_tpu.models import ResNet18

    model = ResNet18(num_classes=10, fused_epilogue=True)  # plain BN
    x = jnp.ones((1, 32, 32, 3))
    with pytest.raises(ValueError, match="fuse_relu"):
        model.init(jax.random.PRNGKey(0), x, train=True)


def test_resnet_groupbn_norm_cls_end_to_end():
    """The imagenet --fused-bn wiring: ResNet over
    contrib.groupbn.BatchNorm2d_NHWC trains a step and keeps its
    keep-bn-fp32-friendly param paths (bn*/bn/scale)."""
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
    from apex_tpu.models import ResNet18

    model = ResNet18(num_classes=10, dtype=jnp.bfloat16,
                     norm_cls=functools.partial(BatchNorm2d_NHWC))
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    assert "bn" in variables["params"]["bn_init"]          # nested module
    y, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
    assert y.shape == (2, 10) and np.isfinite(np.asarray(y)).all()
