"""GPT causal LM: causality, training, attention-impl equivalence, and
sequence-parallel (ring) parity on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import training
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models import gpt_tiny
from apex_tpu.training import make_train_step


def _ids(b=2, t=32, seed=0, vocab=1024):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (b, t)))


def test_causality():
    """Changing token t+k must not change logits at position t."""
    model = gpt_tiny(attention_impl="full")
    ids = _ids()
    params = model.init(jax.random.PRNGKey(0), ids)
    out1 = model.apply(params, ids)
    ids2 = ids.at[:, 20:].set((ids[:, 20:] + 7) % 1024)
    out2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(out1[:, :20]),
                               np.asarray(out2[:, :20]), atol=1e-5)
    assert np.abs(np.asarray(out1[:, 20:]) -
                  np.asarray(out2[:, 20:])).max() > 1e-3


@pytest.mark.parametrize("impl", ["blockwise", "flash"])
def test_attention_impls_match_oracle(impl):
    model_full = gpt_tiny(attention_impl="full")
    model_alt = gpt_tiny(attention_impl=impl)
    ids = _ids(seed=1)
    params = model_full.init(jax.random.PRNGKey(0), ids)
    out_full = model_full.apply(params, ids)
    out_alt = model_alt.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out_alt),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_gpt_lm_training_reduces_loss():
    """Next-token training with the fused xentropy loss at amp O2."""
    model = gpt_tiny(dtype=jnp.bfloat16, attention_impl="flash")
    ids = _ids(b=4, t=32, seed=2)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch[:, :-1])
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]),
            batch[:, 1:].reshape(-1), smoothing=0.0)
        return jnp.mean(losses)

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-3),
                                       opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn)
    losses = []
    for _ in range(8):
        state, m = step(state, ids)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.all(np.isfinite(losses))


def test_gpt_ring_attention_matches_single_device(cpu_mesh):
    """Sequence-parallel GPT (ring attention over 'data'-as-sp axis) equals
    the single-device causal model — the long-context topology."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    T = 32
    model_sp = gpt_tiny(attention_impl="ring", sp_axis="data")
    model_1d = gpt_tiny(attention_impl="full")
    ids = _ids(b=2, t=T, seed=3)
    params = model_1d.init(jax.random.PRNGKey(0), ids)

    def fwd(params, ids_shard):
        return model_sp.apply(params, ids_shard)

    out_sp = jax.jit(shard_map(
        fwd, mesh=cpu_mesh,
        in_specs=(P(), P(None, "data")),
        out_specs=P(None, "data")))(params, ids)
    out_ref = model_1d.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)


# -- KV-cache autoregressive decode -------------------------------------------

@pytest.mark.parametrize("kw", [{}, {"num_kv_heads": 2}, {"window": 12}])
@pytest.mark.slow
def test_generate_matches_full_forward_greedy(kw):
    """generate()'s KV-cache decode must reproduce token-for-token the
    greedy sequence obtained by repeated FULL forward passes — incl. GQA
    caches (kv-head shaped) and sliding-window decode generating well past
    the window length."""
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(max_len=64, **kw)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(1, 1024, (2, 5)))
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]

    n_new = 25                      # window=12 -> generates 2x past it
    out = generate(m, params, prompt, max_new_tokens=n_new)
    ids = prompt
    for _ in range(n_new):
        logits = m.apply({"params": params}, ids)[:, -1]
        ids = jnp.concatenate([ids, jnp.argmax(logits, -1)[:, None]],
                              axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))


@pytest.mark.slow
def test_generate_sampling_and_truncation():
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(max_len=16)
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 1024, (1, 4)))
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]
    # truncates at max_len
    out = generate(m, params, prompt, max_new_tokens=100)
    assert out.shape == (1, 16)
    # temperature sampling: valid ids, reproducible under the same rng
    a = generate(m, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    b = generate(m, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 1024


def test_generate_rejects_sp_models():
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(sp_axis="sp", attention_impl="ring")
    with pytest.raises(ValueError, match="sp_axis"):
        generate(m, {}, jnp.zeros((1, 4), jnp.int32), 4)
