"""GPT causal LM: causality, training, attention-impl equivalence, and
sequence-parallel (ring) parity on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import training
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models import gpt_tiny
from apex_tpu.training import make_train_step


def _ids(b=2, t=32, seed=0, vocab=1024):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (b, t)))


def test_causality():
    """Changing token t+k must not change logits at position t."""
    model = gpt_tiny(attention_impl="full")
    ids = _ids()
    params = model.init(jax.random.PRNGKey(0), ids)
    out1 = model.apply(params, ids)
    ids2 = ids.at[:, 20:].set((ids[:, 20:] + 7) % 1024)
    out2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(out1[:, :20]),
                               np.asarray(out2[:, :20]), atol=1e-5)
    assert np.abs(np.asarray(out1[:, 20:]) -
                  np.asarray(out2[:, 20:])).max() > 1e-3


@pytest.mark.parametrize("impl", ["blockwise", "flash"])
def test_attention_impls_match_oracle(impl):
    model_full = gpt_tiny(attention_impl="full")
    model_alt = gpt_tiny(attention_impl=impl)
    ids = _ids(seed=1)
    params = model_full.init(jax.random.PRNGKey(0), ids)
    out_full = model_full.apply(params, ids)
    out_alt = model_alt.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out_alt),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_gpt_lm_training_reduces_loss():
    """Next-token training with the fused xentropy loss at amp O2."""
    model = gpt_tiny(dtype=jnp.bfloat16, attention_impl="flash")
    ids = _ids(b=4, t=32, seed=2)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch[:, :-1])
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]),
            batch[:, 1:].reshape(-1), smoothing=0.0)
        return jnp.mean(losses)

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-3),
                                       opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn)
    losses = []
    for _ in range(8):
        state, m = step(state, ids)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.all(np.isfinite(losses))


def test_gpt_ring_attention_matches_single_device(cpu_mesh):
    """Sequence-parallel GPT (ring attention over 'data'-as-sp axis) equals
    the single-device causal model — the long-context topology."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    T = 32
    model_sp = gpt_tiny(attention_impl="ring", sp_axis="data")
    model_1d = gpt_tiny(attention_impl="full")
    ids = _ids(b=2, t=T, seed=3)
    params = model_1d.init(jax.random.PRNGKey(0), ids)

    def fwd(params, ids_shard):
        return model_sp.apply(params, ids_shard)

    out_sp = jax.jit(shard_map(
        fwd, mesh=cpu_mesh,
        in_specs=(P(), P(None, "data")),
        out_specs=P(None, "data")))(params, ids)
    out_ref = model_1d.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)


# -- KV-cache autoregressive decode -------------------------------------------

@pytest.mark.parametrize("kw", [{}, {"num_kv_heads": 2}, {"window": 12}])
@pytest.mark.slow
def test_generate_matches_full_forward_greedy(kw):
    """generate()'s KV-cache decode must reproduce token-for-token the
    greedy sequence obtained by repeated FULL forward passes — incl. GQA
    caches (kv-head shaped) and sliding-window decode generating well past
    the window length."""
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(max_len=64, **kw)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(1, 1024, (2, 5)))
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]

    n_new = 25                      # window=12 -> generates 2x past it
    out = generate(m, params, prompt, max_new_tokens=n_new)
    ids = prompt
    for _ in range(n_new):
        logits = m.apply({"params": params}, ids)[:, -1]
        ids = jnp.concatenate([ids, jnp.argmax(logits, -1)[:, None]],
                              axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))


@pytest.mark.slow
def test_generate_sampling_and_truncation():
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(max_len=16)
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 1024, (1, 4)))
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]
    # truncates at max_len
    out = generate(m, params, prompt, max_new_tokens=100)
    assert out.shape == (1, 16)
    # temperature sampling: valid ids, reproducible under the same rng
    a = generate(m, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    b = generate(m, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 1024


def test_generate_rejects_sp_models():
    from apex_tpu.models import gpt_tiny
    from apex_tpu.models.gpt import generate

    m = gpt_tiny(sp_axis="sp", attention_impl="ring")
    with pytest.raises(ValueError, match="sp_axis"):
        generate(m, {}, jnp.zeros((1, 4), jnp.int32), 4)


# -- external-cache incremental forward (ISSUE 11) ----------------------------

@pytest.mark.parametrize("kw", [{}, {"num_kv_heads": 2}])
def test_incremental_forward_matches_full_greedy(kw):
    """The serving-engine forward: prefill once into an external dense
    cache, then single-token decode steps with per-sequence positions —
    must reproduce token-for-token the repeated-full-forward greedy
    sequence (incl. GQA caches, which store only the kv heads)."""
    from apex_tpu.models.gpt import init_cache

    m = gpt_tiny(max_len=64, **kw)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(1, 1024, (2, 6)))
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]

    ids = prompt
    for _ in range(8):
        logits = m.apply({"params": params}, ids)[:, -1]
        ids = jnp.concatenate([ids, jnp.argmax(logits, -1)[:, None]],
                              axis=1)
    ref = np.asarray(ids)

    caches = init_cache(m, 2, cache_len=64)
    if kw.get("num_kv_heads"):      # GQA caches are kv-head shaped
        assert caches[0][0].shape[2] == kw["num_kv_heads"]
    logits, caches = m.apply({"params": params}, prompt,
                             kv_caches=caches,
                             positions=jnp.zeros((2,), jnp.int32))
    tok = jnp.argmax(logits[:, -1], -1)
    out = [np.asarray(tok)]
    pos = jnp.full((2,), prompt.shape[1], jnp.int32)
    for _ in range(7):
        logits, caches = m.apply({"params": params}, tok[:, None],
                                 kv_caches=caches, positions=pos)
        tok = jnp.argmax(logits[:, -1], -1)
        out.append(np.asarray(tok))
        pos = pos + 1
    inc = np.concatenate([np.asarray(prompt), np.stack(out, 1)], axis=1)
    np.testing.assert_array_equal(ref, inc)


def test_incremental_forward_staggered_positions():
    """Continuous batching's defining shape: two sequences at DIFFERENT
    positions in one decode batch.  Each row must match its own
    single-sequence trajectory exactly — the flax-cache path cannot do
    this (one scalar cache_index for the whole batch)."""
    from apex_tpu.models.gpt import init_cache

    m = gpt_tiny(max_len=32)
    rng = np.random.RandomState(1)
    pa = jnp.asarray(rng.randint(1, 1024, (1, 7)))
    pb = jnp.asarray(rng.randint(1, 1024, (1, 3)))
    params = m.init(jax.random.PRNGKey(2), pa)["params"]

    def solo(prompt, n):
        caches = init_cache(m, 1, cache_len=32)
        logits, caches = m.apply(
            {"params": params}, prompt, kv_caches=caches,
            positions=jnp.zeros((1,), jnp.int32))
        tok = jnp.argmax(logits[:, -1], -1)
        toks, pos = [int(tok[0])], prompt.shape[1]
        for _ in range(n - 1):
            logits, caches = m.apply(
                {"params": params}, tok[:, None], kv_caches=caches,
                positions=jnp.full((1,), pos, jnp.int32))
            tok = jnp.argmax(logits[:, -1], -1)
            toks.append(int(tok[0]))
            pos += 1
        return toks

    ref_a, ref_b = solo(pa, 4), solo(pb, 4)

    # batched: prefill each row separately into rows of one 2-deep cache
    caches = init_cache(m, 2, cache_len=32)

    def prefill_row(row, prompt):
        nonlocal caches
        row_caches = [(k[row:row + 1], v[row:row + 1]) for k, v in caches]
        logits, new = m.apply({"params": params}, prompt,
                              kv_caches=row_caches,
                              positions=jnp.zeros((1,), jnp.int32))
        caches = [(k.at[row].set(nk[0]), v.at[row].set(nv[0]))
                  for (k, v), (nk, nv) in zip(caches, new)]
        return int(jnp.argmax(logits[0, -1]))

    t_a = prefill_row(0, pa)
    t_b = prefill_row(1, pb)
    got_a, got_b = [t_a], [t_b]
    pos = jnp.asarray([pa.shape[1], pb.shape[1]], jnp.int32)
    tok = jnp.asarray([t_a, t_b])
    for _ in range(3):
        logits, caches = m.apply({"params": params}, tok[:, None],
                                 kv_caches=caches, positions=pos)
        tok = jnp.argmax(logits[:, -1], -1)
        got_a.append(int(tok[0]))
        got_b.append(int(tok[1]))
        pos = pos + 1
    assert got_a == ref_a and got_b == ref_b


def test_init_cache_validates_len():
    from apex_tpu.models.gpt import init_cache
    m = gpt_tiny(max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        init_cache(m, 1, cache_len=64)
    c = init_cache(m, 3)
    assert len(c) == m.num_layers
    assert c[0][0].shape == (3, 16, m.num_heads,
                             m.hidden_size // m.num_heads)
