"""End-to-end imperative amp loops: O0/O1/O2 parity and skip-step semantics.

Mirrors the reference's heavyweight matrices
(``tests/L0/run_amp/test_multiple_models_optimizers_losses.py``,
``test_fused_sgd.py:47-794``): run the amp path against a manual fp32
reference run, with deliberately injected overflow steps, asserting the
overflow steps are skipped and parameters track the reference (which also
skips those steps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp._amp_state import _amp_state
from apex_tpu.optimizers import FusedSGD, FusedAdam


def _init_params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w1": jnp.asarray(rng.randn(8, 16).astype(np.float32) * 0.1),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4).astype(np.float32) * 0.1),
        "b2": jnp.zeros((4,), jnp.float32),
    }


def _loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w1"].astype(x.dtype)
                 + params["b1"].astype(x.dtype))
    out = h @ params["w2"].astype(x.dtype) + params["b2"].astype(x.dtype)
    return jnp.mean((out.astype(jnp.float32) - y) ** 2)


def _batches(n, seed=42):
    rng = np.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(32, 8).astype(np.float32)),
             jnp.asarray(rng.randn(32, 4).astype(np.float32)))
            for _ in range(n)]


def _reference_run(batches, lr=0.1, skip_steps=()):
    """Manual fp32 SGD, skipping the given step indices."""
    params = _init_params()
    for i, (x, y) in enumerate(batches):
        if i in skip_steps:
            continue
        grads = jax.grad(_loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_amp_loop_tracks_fp32_reference(opt_level):
    batches = _batches(5)
    params = _init_params()
    opt = FusedSGD(params, lr=0.1)
    params, opt = amp.initialize(params, opt, opt_level=opt_level, verbosity=0)
    for x, y in batches:
        loss, grads = opt.value_and_grad(_loss_fn)(x, y)
        with amp.scale_loss(loss, opt) as scaled_loss:
            opt.backward(grads)
        opt.step()
    expected = _reference_run(batches)
    # bf16 storage costs precision; tolerance ladder like the reference's
    # fp16 comparisons (two_gpu_unit_test.py:40-46).
    tol = 1e-6 if opt_level in ("O0",) else 2e-2
    for k in expected:
        np.testing.assert_allclose(np.asarray(opt.params[k], np.float32),
                                   np.asarray(expected[k]), atol=tol, rtol=tol,
                                   err_msg=f"{opt_level}/{k}")
    amp.shutdown()  # undo O1 patches for test isolation


def test_o2_master_weights_exist_and_are_fp32():
    params = _init_params()
    opt = FusedAdam(params, lr=1e-3)
    params, opt = amp.initialize(params, opt, opt_level="O2", verbosity=0)
    assert opt.master_params is not None
    for leaf in jax.tree_util.tree_leaves(opt.master_params):
        assert leaf.dtype == jnp.float32
    # model params are bf16 (no norm layers in this net)
    assert opt.params["w1"].dtype == jnp.bfloat16


def test_overflow_skips_step_and_halves_scale():
    batches = _batches(6)
    params = _init_params()
    opt = FusedSGD(params, lr=0.1)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale="dynamic", verbosity=0)
    start_scale = _amp_state.loss_scalers[0].loss_scale()
    skip_at = 2
    for i, (x, y) in enumerate(batches):
        loss, grads = opt.value_and_grad(_loss_fn)(x, y)
        if i == skip_at:
            grads = jax.tree_util.tree_map(jnp.copy, grads)
            grads["w1"] = grads["w1"].at[0, 0].set(jnp.inf)
        with amp.scale_loss(loss, opt):
            opt.backward(grads)
        opt.step()
    assert _amp_state.loss_scalers[0].loss_scale() == start_scale / 2
    expected = _reference_run(batches, skip_steps={skip_at})
    for k in expected:
        np.testing.assert_allclose(np.asarray(opt.params[k], np.float32),
                                   np.asarray(expected[k]), atol=2e-2,
                                   rtol=2e-2, err_msg=k)


def test_grad_accumulation_delay_unscale():
    """Two micro-batches accumulated, then one step; equals one step on the
    summed grads (reference delay_unscale contract)."""
    (x1, y1), (x2, y2) = _batches(2)
    params = _init_params()
    opt = FusedSGD(params, lr=0.1)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale=128.0, verbosity=0)

    loss1, g1 = opt.value_and_grad(_loss_fn)(x1, y1)
    with amp.scale_loss(loss1, opt, delay_unscale=True):
        opt.backward(g1)
    loss2, g2 = opt.value_and_grad(_loss_fn)(x2, y2)
    with amp.scale_loss(loss2, opt):
        opt.backward(g2)
    opt.step()

    # Reference: single step with summed fp32 grads.
    ref = _init_params()
    ga = jax.grad(_loss_fn)(ref, x1, y1)
    gb = jax.grad(_loss_fn)(ref, x2, y2)
    expected = jax.tree_util.tree_map(
        lambda p, a, b: p - 0.1 * (a + b), ref, ga, gb)
    for k in expected:
        np.testing.assert_allclose(np.asarray(opt.params[k], np.float32),
                                   np.asarray(expected[k]), atol=2e-2,
                                   rtol=2e-2, err_msg=k)


def test_fused_sgd_no_materialize_master_grads():
    """The FusedSGD fused-unscale path (materialize_master_grads=False)
    matches the materialized path (reference test_fused_sgd.py matrix)."""
    batches = _batches(4)
    results = []
    for mat in (True, False):
        params = _init_params()
        opt = FusedSGD(params, lr=0.1, momentum=0.9,
                       materialize_master_grads=mat)
        params, opt = amp.initialize(params, opt, opt_level="O2",
                                     loss_scale=64.0, verbosity=0)
        for x, y in batches:
            loss, grads = opt.value_and_grad(_loss_fn)(x, y)
            with amp.scale_loss(loss, opt):
                opt.backward(grads)
            opt.step()
        results.append(opt.master_params)
    for k in results[0]:
        np.testing.assert_allclose(np.asarray(results[0][k]),
                                   np.asarray(results[1][k]),
                                   atol=1e-3, rtol=1e-3, err_msg=k)


def test_multiple_losses_and_scalers():
    """num_losses=2 with independent dynamic scalers (reference
    test_multiple_models_optimizers_losses.py)."""
    params = _init_params()
    opt = FusedSGD(params, lr=0.05)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale="dynamic", num_losses=2,
                                 verbosity=0)
    (x1, y1), (x2, y2) = _batches(2)

    loss1, g1 = opt.value_and_grad(_loss_fn)(x1, y1)
    with amp.scale_loss(loss1, opt, loss_id=0):
        opt.backward(g1)
    opt.step()

    g_bad = jax.tree_util.tree_map(lambda g: g.at[(0,) * g.ndim].set(jnp.nan)
                                   if g.ndim else g, jax.grad(_loss_fn)(opt.master_params, x2, y2))
    with amp.scale_loss(loss1, opt, loss_id=1):
        opt.backward(g_bad)
    opt.step()

    sd = amp.state_dict()
    assert sd["loss_scaler0"]["loss_scale"] == 2.**16     # untouched
    assert sd["loss_scaler1"]["loss_scale"] == 2.**15     # halved


def test_accum_steps_matches_full_batch():
    """accum_steps=N compiled into the step reproduces the full-batch
    trajectory exactly for a mean-reduced loss (the jitted analog of the
    reference's delay_unscale micro-batch contract)."""
    from apex_tpu import training
    from apex_tpu.training import make_train_step

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 4) * 0.3, jnp.float32)}
    x = jnp.asarray(rng.randn(16, 6), jnp.float32)
    y = jnp.asarray(rng.randn(16, 4) * 0.1, jnp.float32)

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"].astype(xb.dtype) - yb) ** 2)

    def run(accum):
        init_fn, step_fn = make_train_step(
            loss_fn, training.adam(1e-2), opt_level="O2",
            loss_scale="dynamic", accum_steps=accum)
        state = init_fn(params)
        step = jax.jit(step_fn)
        losses = []
        for _ in range(5):
            state, m = step(state, (x, y))
            losses.append(float(m["loss"]))
        return np.asarray(losses)

    full = run(1)
    accum4 = run(4)
    np.testing.assert_allclose(accum4, full, rtol=1e-5, atol=1e-7)


def test_accum_steps_threads_model_state():
    """Batch stats update sequentially across microbatches (N real steps'
    worth of EMA updates, like the reference's accumulation loop)."""
    import flax.linen as nn
    from apex_tpu import training
    from apex_tpu.training import make_train_step

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.BatchNorm(use_running_average=not train,
                             name="bn")(x)
            return nn.Dense(2, name="d")(x)

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 3) * 2 + 1, jnp.float32)
    y = jnp.asarray(rng.randn(8, 2), jnp.float32)
    model = M()
    variables = model.init(jax.random.PRNGKey(0), x)
    params, bs = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        xb, yb = batch
        out, upd = model.apply({"params": p, "batch_stats": ms}, xb,
                               train=True, mutable=["batch_stats"])
        return jnp.mean((out - yb) ** 2), upd["batch_stats"]

    init_fn, step_fn = make_train_step(
        loss_fn, training.sgd(1e-2), opt_level="O0", accum_steps=2,
        has_model_state=True)
    state = init_fn(params, bs)
    state, m = jax.jit(step_fn)(state, (x, y))
    # stats moved away from init (mean 0 / var 1) and are finite
    assert not np.allclose(np.asarray(state.model_state["bn"]["mean"]), 0.0)
    assert np.all(np.isfinite(
        np.asarray(jax.tree_util.tree_leaves(state.model_state)[0])))


def test_accum_steps_rejects_indivisible_batch():
    from apex_tpu import training
    from apex_tpu.training import make_train_step

    def loss_fn(p, batch):
        return jnp.mean(batch @ p["w"])

    init_fn, step_fn = make_train_step(loss_fn, training.sgd(1e-2),
                                       opt_level="O0", accum_steps=3)
    state = init_fn({"w": jnp.ones((4, 2))})
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(step_fn)(state, jnp.ones((8, 4)))


def test_chain_steps_matches_per_call_trajectory():
    """K steps compiled into one program (training.chain_steps — the
    device-loop shape the bench headline uses) must produce the same
    trajectory as K jitted-per-step calls on the same batch sequence."""
    from apex_tpu import training
    from apex_tpu.training import chain_steps, make_train_step

    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(12, 24) / 4, jnp.float32),
              "w2": jnp.asarray(rng.randn(24, 3) / 5, jnp.float32)}

    def loss_fn(p, batch):
        xb, yb = batch
        z = jnp.tanh(xb @ p["w1"]) @ p["w2"]
        return jnp.mean((z.astype(jnp.float32) - yb) ** 2)

    init_fn, step_fn = make_train_step(
        loss_fn, training.sgd(0.05, momentum=0.9), opt_level="O2",
        loss_scale="dynamic")
    xs = jnp.asarray(rng.randn(6, 8, 12), jnp.float32)
    ys = jnp.asarray(rng.randn(6, 8, 3), jnp.float32)

    state_a = init_fn(params)
    step = jax.jit(step_fn)
    per_call = []
    for i in range(6):
        state_a, m = step(state_a, (xs[i], ys[i]))
        per_call.append(float(m["loss"]))

    state_b = init_fn(params)
    chained = jax.jit(chain_steps(step_fn))
    state_b, ms = chained(state_b, (xs, ys))
    np.testing.assert_allclose(np.asarray(ms["loss"]), per_call, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                    jax.tree_util.tree_leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_fused_sgd_no_materialize_skips_on_deferred_overflow():
    """Regression (r4): scale_loss defers the overflow-flag read to
    step(); FusedSGD's no-materialize FAST PATH must resolve the pending
    flags before its gate, or an overflowed update would be applied
    (the sync-era code armed the latch inside scale_loss, so the fast
    path's `not _skip_next_step` check was then sufficient)."""
    params = _init_params()
    opt = FusedSGD(params, lr=0.1, momentum=0.9,
                   materialize_master_grads=False)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale="dynamic", verbosity=0)
    x, y = _batches(1)[0]
    loss, grads = opt.value_and_grad(_loss_fn)(x, y)
    with amp.scale_loss(loss, opt):
        opt.backward(grads)
    opt.step()
    before = {k: np.asarray(v) for k, v in opt.master_params.items()}
    scale_before = _amp_state.loss_scalers[0].loss_scale()
    # Inf gradients -> deferred overflow flag -> step() must skip.
    bad = jax.tree_util.tree_map(
        lambda g: jnp.full_like(g, jnp.inf), grads)
    with amp.scale_loss(loss, opt):
        opt.backward(bad)
    opt.step()
    for k, v in opt.master_params.items():
        np.testing.assert_array_equal(np.asarray(v), before[k], err_msg=k)
    assert _amp_state.loss_scalers[0].loss_scale() == scale_before / 2
