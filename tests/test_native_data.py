"""Native C++ runtime + input pipeline tests (reference apex_C
flatten/unflatten contract + data_prefetcher semantics)."""

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import native
from apex_tpu.data import (PrefetchLoader, normalize_images,
                           synthetic_imagenet, IMAGENET_MEAN, IMAGENET_STD)


def _arrays():
    rng = np.random.RandomState(0)
    return [rng.randn(4, 3).astype(np.float32),
            rng.randn(7).astype(np.float64),
            rng.randint(0, 100, (2, 2, 2)).astype(np.int32)]


def test_flatten_unflatten_roundtrip():
    arrays = _arrays()
    flat = native.flatten(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = native.unflatten(flat, arrays)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_native_library_builds():
    """The C++ tier should be active in this image (g++ baked in)."""
    native._load()
    assert native.available, "native runtime failed to build/load"


def test_unflatten_size_mismatch_raises():
    arrays = _arrays()
    flat = native.flatten(arrays)
    with pytest.raises(ValueError, match="bytes"):
        native.unflatten(flat[:-8], arrays)


def test_u8_normalize_matches_numpy():
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (3, 8, 8, 3), dtype=np.uint8)
    got = normalize_images(imgs)
    mean = np.asarray(IMAGENET_MEAN, np.float32)
    std = np.asarray(IMAGENET_STD, np.float32)
    want = (imgs.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_u8_normalize_validates_channels():
    imgs = np.zeros((1, 4, 4, 3), np.uint8)
    with pytest.raises(ValueError, match="channel"):
        native.u8_to_f32_nhwc(imgs, [0.5], [0.5])


def test_prefetch_loader_order_and_device():
    batches = [(np.full((2, 2), i, np.float32), i) for i in range(5)]
    out = list(PrefetchLoader(iter(batches), depth=2))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert float(x[0, 0]) == i and y == i
        assert isinstance(x, jnp.ndarray)   # device-put happened


def test_prefetch_loader_propagates_errors():
    def gen():
        yield (np.zeros((1,)),)
        raise RuntimeError("decode failed")

    it = iter(PrefetchLoader(gen(), depth=1))
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_prefetch_abandoned_iterator_releases_producer():
    """Regression: breaking out of the loop must not leave the producer
    thread blocked on the bounded queue forever."""
    import threading
    import time
    started = threading.active_count()
    batches = [(np.zeros((2, 2), np.float32), i) for i in range(50)]
    it = iter(PrefetchLoader(iter(batches), depth=1))
    next(it)
    it.close()          # what `break` in a for-loop does via GeneratorExit
    deadline = time.time() + 5
    while threading.active_count() > started and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= started, "producer thread leaked"


def test_prefetch_close_releases_thread_and_queued_batches():
    """ISSUE-2 satellite: the stop-Event shutdown path.  After
    ``close()`` the producer thread must exit, the source iterator must
    stop being consumed, and the batches staged ahead in the queue must
    actually be dropped (their weakrefs die) — an abandoned half-epoch
    cannot pin the prefetch depth's worth of device memory."""
    import gc
    import threading
    import time
    import weakref

    class _Probe:
        """Leaf without .shape: queued as-is (no device_put), so the
        queue's reference is the only thing keeping it alive."""

    produced = []

    def gen():
        for _ in range(100):
            p = _Probe()
            produced.append(weakref.ref(p))
            yield p

    loader = PrefetchLoader(gen(), depth=3)
    it = iter(loader)
    first = next(it)
    assert any(t.name == "apex-tpu-prefetch" and t.is_alive()
               for t in threading.enumerate())
    loader.close()
    deadline = time.time() + 5
    while any(t.name == "apex-tpu-prefetch" and t.is_alive()
              for t in threading.enumerate()) and time.time() < deadline:
        time.sleep(0.05)
    assert not any(t.name == "apex-tpu-prefetch" and t.is_alive()
                   for t in threading.enumerate()), "producer survived close"
    # the producer gave up early: it staged at most depth+2 of the 100
    assert len(produced) < 100
    assert first is not None
    # resuming iteration after close() must terminate (close re-arms the
    # end-of-stream sentinel), not hang on the dead producer's queue
    assert next(it, None) is None
    # drop the consumer's own references (the delivered batch and the
    # iterator frame whose `item` local aliases it) — what remains alive
    # after this is whatever the loader itself still pins
    del first, it
    gc.collect()
    alive = [r for r in produced if r() is not None]
    # nothing queued may survive close(); the only tolerated survivor is
    # the SOURCE generator's own last-yielded local (its frame is still
    # suspended inside loader._it)
    assert len(alive) <= 1, f"{len(alive)} queued batches leaked"
    # closing again is a no-op, and the context-manager form works
    loader.close()
    with PrefetchLoader(iter([(np.zeros((2,)),)]), depth=1) as lo:
        assert len(list(lo)) == 1


def test_prefetch_with_native_transform():
    stream = synthetic_imagenet(batch_size=2, image_size=16, steps=3)
    loader = PrefetchLoader(
        stream, transform=lambda b: (normalize_images(b[0]), b[1]))
    seen = 0
    for x, y in loader:
        assert x.shape == (2, 16, 16, 3) and x.dtype == jnp.float32
        seen += 1
    assert seen == 3


def test_directory_imagenet_decodes_jpeg(tmp_path):
    """The honest-scope JPEG path: PIL decode + resize through the
    threaded pool, labels from directory names (reference leans on
    DALI/torchvision here — examples/imagenet/main_amp.py:262-310)."""
    pytest.importorskip("PIL")
    from PIL import Image

    from apex_tpu.data import directory_imagenet

    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            arr = rng.randint(0, 255, (40, 52, 3)).astype(np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.jpg")

    batches = list(directory_imagenet(str(tmp_path), batch_size=2,
                                      image_size=32))
    assert batches, "no batches yielded"
    imgs, labels = batches[0]
    assert imgs.shape == (2, 32, 32, 3) and imgs.dtype == np.uint8
    assert set(np.unique([l for _, ls in batches for l in ls])) <= {0, 1}
