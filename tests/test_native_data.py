"""Native C++ runtime + input pipeline tests (reference apex_C
flatten/unflatten contract + data_prefetcher semantics + the ISSUE-3
multi-worker input engine: worker-pool delivery, error channel, shutdown
under load, native synthetic generation, multi-epoch / sharded
directory streaming).  The whole file must pass under
``APEX_TPU_DISABLE_NATIVE=1`` too (two-tier install contract)."""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import native
from apex_tpu.data import (BatchFiles, LoaderError, PrefetchLoader,
                           augment_images, directory_imagenet, load_batch,
                           normalize_images, synthetic_imagenet,
                           format_loader_line,
                           IMAGENET_MEAN, IMAGENET_STD)

_NATIVE_DISABLED = bool(os.environ.get("APEX_TPU_DISABLE_NATIVE"))


def _no_prefetch_threads():
    return not any(t.name.startswith("apex-tpu-prefetch") and t.is_alive()
                   for t in threading.enumerate())


def _await_prefetch_exit(timeout=5.0):
    deadline = time.time() + timeout
    while not _no_prefetch_threads() and time.time() < deadline:
        time.sleep(0.05)
    return _no_prefetch_threads()


def _arrays():
    rng = np.random.RandomState(0)
    return [rng.randn(4, 3).astype(np.float32),
            rng.randn(7).astype(np.float64),
            rng.randint(0, 100, (2, 2, 2)).astype(np.int32)]


def test_flatten_unflatten_roundtrip():
    arrays = _arrays()
    flat = native.flatten(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = native.unflatten(flat, arrays)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


@pytest.mark.skipif(_NATIVE_DISABLED,
                    reason="APEX_TPU_DISABLE_NATIVE forces the python tier")
def test_native_library_builds():
    """The C++ tier should be active in this image (g++ baked in)."""
    native._load()
    assert native.available, "native runtime failed to build/load"


def test_unflatten_size_mismatch_raises():
    arrays = _arrays()
    flat = native.flatten(arrays)
    with pytest.raises(ValueError, match="bytes"):
        native.unflatten(flat[:-8], arrays)


def test_u8_normalize_matches_numpy():
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (3, 8, 8, 3), dtype=np.uint8)
    got = normalize_images(imgs)
    mean = np.asarray(IMAGENET_MEAN, np.float32)
    std = np.asarray(IMAGENET_STD, np.float32)
    want = (imgs.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_u8_normalize_validates_channels():
    imgs = np.zeros((1, 4, 4, 3), np.uint8)
    with pytest.raises(ValueError, match="channel"):
        native.u8_to_f32_nhwc(imgs, [0.5], [0.5])


def test_prefetch_loader_order_and_device():
    batches = [(np.full((2, 2), i, np.float32), i) for i in range(5)]
    out = list(PrefetchLoader(iter(batches), depth=2))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert float(x[0, 0]) == i and y == i
        assert isinstance(x, jnp.ndarray)   # device-put happened


def test_prefetch_loader_propagates_errors():
    def gen():
        yield (np.zeros((1,)),)
        raise RuntimeError("decode failed")

    it = iter(PrefetchLoader(gen(), depth=1))
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_prefetch_abandoned_iterator_releases_producer():
    """Regression: breaking out of the loop must not leave the producer
    thread blocked on the bounded queue forever."""
    import threading
    import time
    started = threading.active_count()
    batches = [(np.zeros((2, 2), np.float32), i) for i in range(50)]
    it = iter(PrefetchLoader(iter(batches), depth=1))
    next(it)
    it.close()          # what `break` in a for-loop does via GeneratorExit
    deadline = time.time() + 5
    while threading.active_count() > started and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= started, "producer thread leaked"


def test_prefetch_close_releases_thread_and_queued_batches():
    """ISSUE-2 satellite: the stop-Event shutdown path.  After
    ``close()`` the producer thread must exit, the source iterator must
    stop being consumed, and the batches staged ahead in the queue must
    actually be dropped (their weakrefs die) — an abandoned half-epoch
    cannot pin the prefetch depth's worth of device memory."""
    import gc
    import threading
    import time
    import weakref

    class _Probe:
        """Leaf without .shape: queued as-is (no device_put), so the
        queue's reference is the only thing keeping it alive."""

    produced = []

    def gen():
        for _ in range(100):
            p = _Probe()
            produced.append(weakref.ref(p))
            yield p

    loader = PrefetchLoader(gen(), depth=3)
    it = iter(loader)
    first = next(it)
    assert any(t.name == "apex-tpu-prefetch" and t.is_alive()
               for t in threading.enumerate())
    loader.close()
    deadline = time.time() + 5
    while any(t.name == "apex-tpu-prefetch" and t.is_alive()
              for t in threading.enumerate()) and time.time() < deadline:
        time.sleep(0.05)
    assert not any(t.name == "apex-tpu-prefetch" and t.is_alive()
                   for t in threading.enumerate()), "producer survived close"
    # the producer gave up early: it staged at most depth+2 of the 100
    assert len(produced) < 100
    assert first is not None
    # resuming iteration after close() must terminate (close re-arms the
    # end-of-stream sentinel), not hang on the dead producer's queue
    assert next(it, None) is None
    # drop the consumer's own references (the delivered batch and the
    # iterator frame whose `item` local aliases it) — what remains alive
    # after this is whatever the loader itself still pins
    del first, it
    gc.collect()
    alive = [r for r in produced if r() is not None]
    # nothing queued may survive close(); the only tolerated survivor is
    # the SOURCE generator's own last-yielded local (its frame is still
    # suspended inside loader._it)
    assert len(alive) <= 1, f"{len(alive)} queued batches leaked"
    # closing again is a no-op, and the context-manager form works
    loader.close()
    with PrefetchLoader(iter([(np.zeros((2,)),)]), depth=1) as lo:
        assert len(list(lo)) == 1


def test_prefetch_with_native_transform():
    stream = synthetic_imagenet(batch_size=2, image_size=16, steps=3)
    loader = PrefetchLoader(
        stream, transform=lambda b: (normalize_images(b[0]), b[1]))
    seen = 0
    for x, y in loader:
        assert x.shape == (2, 16, 16, 3) and x.dtype == jnp.float32
        seen += 1
    assert seen == 3


def test_directory_imagenet_decodes_jpeg(tmp_path):
    """The honest-scope JPEG path: PIL decode + resize through the
    threaded pool, labels from directory names (reference leans on
    DALI/torchvision here — examples/imagenet/main_amp.py:262-310)."""
    pytest.importorskip("PIL")
    from PIL import Image

    from apex_tpu.data import directory_imagenet

    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            arr = rng.randint(0, 255, (40, 52, 3)).astype(np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.jpg")

    batches = list(directory_imagenet(str(tmp_path), batch_size=2,
                                      image_size=32))
    assert batches, "no batches yielded"
    imgs, labels = batches[0]
    assert imgs.shape == (2, 32, 32, 3) and imgs.dtype == np.uint8
    assert set(np.unique([l for _, ls in batches for l in ls])) <= {0, 1}


# -- native synthetic generation + fused augment (ISSUE 3) --------------------

def test_synth_bytes_deterministic_and_ragged():
    a = native.synth_bytes(1000, seed=7)
    b = native.synth_bytes(1000, seed=7)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.uint8 and a.shape == (1000,)
    assert not np.array_equal(a, native.synth_bytes(1000, seed=8))
    # ragged tail (not a multiple of the 8-byte block) and empty
    assert native.synth_bytes(13, seed=1).shape == (13,)
    np.testing.assert_array_equal(native.synth_bytes(13, seed=1),
                                  native.synth_bytes(16, seed=1)[:13])
    assert native.synth_bytes(0, seed=1).shape == (0,)
    with pytest.raises(ValueError, match=">= 0"):
        native.synth_bytes(-1, seed=0)


def test_synthetic_imagenet_native_stream():
    """The counter-based generator: deterministic in (seed, step),
    distinct across steps, int32 labels in range."""
    run1 = list(synthetic_imagenet(2, image_size=16, num_classes=10,
                                   steps=3, seed=5))
    run2 = list(synthetic_imagenet(2, image_size=16, num_classes=10,
                                   steps=3, seed=5))
    assert len(run1) == 3
    for (i1, l1), (i2, l2) in zip(run1, run2):
        assert i1.shape == (2, 16, 16, 3) and i1.dtype == np.uint8
        assert l1.dtype == np.int32
        assert (l1 >= 0).all() and (l1 < 10).all()
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(l1, l2)
    assert not np.array_equal(run1[0][0], run1[1][0])


def test_augment_images_fused_matches_reference():
    """The fused crop/flip/normalize epilogue == the three-pass numpy
    reference, on whichever tier is active."""
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 256, (4, 12, 14, 3), dtype=np.uint8)
    offsets = np.array([[0, 0], [4, 6], [2, 3], [1, 5]], np.int32)
    flips = np.array([0, 1, 1, 0], np.uint8)
    got = native.crop_flip_normalize(imgs, 8, offsets, flips,
                                     IMAGENET_MEAN, IMAGENET_STD)
    mean = np.asarray(IMAGENET_MEAN, np.float32)
    std = np.asarray(IMAGENET_STD, np.float32)
    for i in range(4):
        oy, ox = offsets[i]
        crop = imgs[i, oy:oy + 8, ox:ox + 8]
        if flips[i]:
            crop = crop[:, ::-1]
        np.testing.assert_allclose(
            got[i], (crop.astype(np.float32) / 255.0 - mean) / std,
            atol=1e-5)
    # the rng-driving wrapper: shape/dtype contract + determinism per rng
    out = augment_images(imgs, 8, np.random.RandomState(0))
    np.testing.assert_array_equal(
        out, augment_images(imgs, 8, np.random.RandomState(0)))
    assert out.shape == (4, 8, 8, 3) and out.dtype == np.float32


def test_crop_flip_normalize_validates():
    imgs = np.zeros((2, 8, 8, 3), np.uint8)
    with pytest.raises(ValueError, match="exceeds"):
        native.crop_flip_normalize(imgs, 9, np.zeros((2, 2), np.int32),
                                   np.zeros(2, np.uint8),
                                   IMAGENET_MEAN, IMAGENET_STD)
    with pytest.raises(ValueError, match="out of bounds"):
        native.crop_flip_normalize(imgs, 4,
                                   np.array([[0, 0], [5, 0]], np.int32),
                                   np.zeros(2, np.uint8),
                                   IMAGENET_MEAN, IMAGENET_STD)


# -- multi-worker engine: delivery, error channel, shutdown (ISSUE 3) ---------

def test_prefetch_multiworker_ordered_delivery():
    batches = [(np.full((2, 2), i, np.float32), i) for i in range(30)]
    with PrefetchLoader(iter(batches), depth=2, workers=4,
                        transform=lambda b: (b[0] * 2, b[1])) as lo:
        out = list(lo)
    assert [y for _, y in out] == list(range(30))
    assert all(float(x[0, 0]) == 2 * i for i, (x, _) in enumerate(out))
    snap = lo.stats.snapshot()
    assert snap["batches"] == 30 and snap["produce_s"] >= 0.0


def test_prefetch_multiworker_unordered_delivers_all():
    batches = [(np.full((1,), i, np.float32),) for i in range(25)]
    with PrefetchLoader(iter(batches), depth=3, workers=4,
                        ordered=False) as lo:
        seen = sorted(int(x[0]) for (x,) in lo)
    assert seen == list(range(25))


def test_prefetch_worker_crash_surfaces_original_exception():
    """ISSUE-3 satellite: a transform crash on ANY worker mid-epoch must
    deliver every earlier batch, then re-raise the ORIGINAL exception
    object in the consumer — not a generic queue error, not a hang."""
    boom = RuntimeError("decode exploded on a worker")

    def transform(b):
        if b[1] == 7:
            raise boom
        return b

    batches = [(np.full((2,), i, np.float32), i) for i in range(20)]
    got = []
    with PrefetchLoader(iter(batches), depth=2, workers=3,
                        transform=transform) as lo:
        with pytest.raises(RuntimeError) as ei:
            for b in lo:
                got.append(b[1])
    assert ei.value is boom
    assert got == list(range(7))
    assert _await_prefetch_exit(), "threads survived the crash"


def test_prefetch_source_crash_multiworker():
    """A crash in the SOURCE iterator itself (not the transform) takes
    the same error channel."""
    def gen():
        for i in range(5):
            yield (np.zeros((1,)), i)
        raise OSError("source died")

    with PrefetchLoader(gen(), depth=1, workers=3) as lo:
        with pytest.raises(OSError, match="source died"):
            n = 0
            for _ in lo:
                n += 1
    assert n == 5


def test_error_channel_is_a_class_not_a_tuple_sentinel():
    """ISSUE-3 satellite regression: a legitimate batch that LOOKS like
    the old ``("__error__", e)`` tuple must flow through as data, and no
    numpy elementwise comparison warning may fire on normal batches."""
    import warnings

    sneaky = ("__error__", np.arange(3))
    batches = [(np.arange(4), np.int32(0)), sneaky]
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with PrefetchLoader(iter(batches), depth=2) as lo:
            out = list(lo)
    assert len(out) == 2
    assert out[1][0] == "__error__"      # delivered as data, not raised
    assert isinstance(LoaderError(ValueError("x")).exc, ValueError)


def test_prefetch_close_under_load_multiworker():
    """ISSUE-3 satellite: ``close()`` while 4 workers are mid-transform
    must leave no live pipeline threads and no staged batches (their
    weakrefs die once the consumer's references drop)."""
    import gc
    import weakref

    class _Probe:
        pass                       # no .shape: staged as-is, queue holds
                                   # the only reference

    produced = []

    def gen():
        for _ in range(200):
            p = _Probe()
            produced.append(weakref.ref(p))
            yield p

    def slow_transform(p):
        time.sleep(0.01)
        return p

    loader = PrefetchLoader(gen(), depth=3, workers=4,
                            transform=slow_transform)
    it = iter(loader)
    first = next(it)
    loader.close()
    assert _await_prefetch_exit(), "pipeline threads survived close()"
    # workers gave up early: at most lookahead (workers+depth) + depth
    # staged + a few in flight of the 200 were ever produced
    assert len(produced) < 50
    assert next(it, None) is None    # close re-arms end-of-stream
    del first, it
    gc.collect()
    alive = [r for r in produced if r() is not None]
    assert len(alive) <= 1, f"{len(alive)} staged batches leaked"


def test_loader_stats_line_matches_bench_regex():
    """The ``loader: stall X%`` line the examples print is the bench.py
    contract — keep the prefix parseable."""
    import re

    from apex_tpu.prof import loader_ledger

    with PrefetchLoader(iter([(np.zeros((2,)),)] * 4), depth=1) as lo:
        list(lo)
    snap = lo.stats.snapshot()
    for key in ("batches", "staged", "elapsed_s", "produce_s",
                "producer_stall_s", "stage_s", "consumer_wait_s",
                "mean_queue_depth", "loader_stall_pct"):
        assert key in snap, key
    assert snap["staged"] >= snap["batches"]
    assert 0.0 <= snap["loader_stall_pct"] <= 100.0
    line = format_loader_line(snap)
    m = re.search(r"loader: stall ([\d.]+)%", line)   # bench._LOADER_RE
    assert m and float(m.group(1)) == pytest.approx(
        snap["loader_stall_pct"], abs=0.01)
    led = loader_ledger(snap, bytes_per_batch=1e6)
    if snap["elapsed_s"] > 0:
        assert "producer_stall_pct" in led and "stage_pct" in led
    if snap["stage_s"]:
        assert led["stage_bw_gb_s"] > 0


def test_prefetch_loader_rejects_bad_workers():
    with pytest.raises(ValueError, match="workers"):
        PrefetchLoader(iter([]), workers=0)


def test_staging_failure_surfaces_not_hangs():
    """Review fix: a device_put failure on the STAGING thread (OOM, an
    unsupported leaf with a .shape attr) must travel the error channel —
    an unhandled exception there would kill the thread and leave the
    consumer blocked in q.get() forever."""
    class Unstageable:
        shape = (2,)              # claims stageability, device_put chokes

    batches = [(np.zeros((2,)),), (Unstageable(),), (np.ones((2,)),)]
    with PrefetchLoader(iter(batches), depth=1) as lo:
        it = iter(lo)
        next(it)                  # batch 0 stages fine
        with pytest.raises(Exception):
            next(it)              # batch 1: staging error, re-raised
    assert _await_prefetch_exit(), "stager thread leaked after failure"


# -- directory streaming: multi-epoch, sharded, decode=False (ISSUE 3) --------

def _npy_tree(tmp_path, per_class=5, size=8):
    rng = np.random.RandomState(0)
    for cls in ("ant", "bee"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(per_class):
            np.save(d / f"s{i}.npy",
                    rng.randint(0, 256, (size, size, 3)).astype(np.uint8))
    return str(tmp_path)


def test_directory_imagenet_multi_epoch_reshuffle(tmp_path):
    """ISSUE-3 satellite: per-epoch reshuffle with per-epoch drop_last —
    every epoch yields the same number of full batches over the same
    sample multiset, in a different (deterministic) order."""
    root = _npy_tree(tmp_path, per_class=5)   # 10 samples, batch 4 -> 2
    out = list(directory_imagenet(root, batch_size=4, image_size=8,
                                  epochs=3, seed=11))
    assert len(out) == 3 * 2                  # drop_last per epoch
    epochs = [out[i:i + 2] for i in range(0, 6, 2)]
    orders = [tuple(int(l) for _, ls in ep for l in ls) for ep in epochs]
    assert orders[0] != orders[1] or orders[1] != orders[2], \
        "epochs were not reshuffled"
    # determinism: the same seed replays the same epoch orders
    replay = list(directory_imagenet(root, batch_size=4, image_size=8,
                                     epochs=3, seed=11))
    for (a, la), (b, lb) in zip(out, replay):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
    # epochs=None streams forever: pull past one epoch and stop
    import itertools
    unending = directory_imagenet(root, batch_size=4, image_size=8,
                                  epochs=None)
    assert len(list(itertools.islice(unending, 5))) == 5
    unending.close()


def test_directory_imagenet_host_shard(tmp_path):
    """Per-host sharding: hosts split each epoch's batch stream
    disjointly and exhaustively (batch granularity, shared shuffle)."""
    root = _npy_tree(tmp_path, per_class=8)   # 16 samples, batch 2 -> 8
    full = list(directory_imagenet(root, batch_size=2, image_size=8,
                                   seed=3))
    shards = [list(directory_imagenet(root, batch_size=2, image_size=8,
                                      seed=3, host_shard=(i, 2)))
              for i in range(2)]
    assert len(shards[0]) == len(shards[1]) == len(full) // 2
    interleaved = [b for pair in zip(*shards) for b in pair]
    for (a, la), (b, lb) in zip(full, interleaved):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
    with pytest.raises(ValueError, match="host_shard"):
        list(directory_imagenet(root, batch_size=2, image_size=8,
                                host_shard=(2, 2)))


def test_directory_imagenet_host_shard_equal_counts(tmp_path):
    """Review fix: when the per-epoch batch count does not divide over
    the hosts, the remainder is dropped on EVERY host — unequal counts
    would deadlock SPMD collectives at the epoch boundary."""
    root = _npy_tree(tmp_path, per_class=9)   # 18 samples, batch 2 -> 9
    counts = [len(list(directory_imagenet(root, batch_size=2,
                                          image_size=8, seed=0,
                                          epochs=2, host_shard=(i, 2))))
              for i in range(2)]
    assert counts[0] == counts[1] == 2 * (9 // 2)


def test_batchfiles_seq_is_global_across_epochs(tmp_path):
    """Review fix: ``BatchFiles.seq`` must keep counting across epochs
    so per-batch augmentation seeds derived from it never repeat, even
    when an epoch reshuffle leads a batch with the same file."""
    root = _npy_tree(tmp_path, per_class=4)   # 8 samples, batch 4 -> 2
    tasks = list(directory_imagenet(root, batch_size=4, image_size=8,
                                    epochs=3, decode=False))
    assert [t.seq for t in tasks] == list(range(6))


def test_directory_decode_false_through_worker_pool(tmp_path):
    """The decode=False protocol: the source yields cheap BatchFiles
    descriptors; the worker pool decodes whole batches via load_batch
    in the transform (no per-batch map barrier)."""
    root = _npy_tree(tmp_path, per_class=4, size=8)   # 8 samples
    stream = directory_imagenet(root, batch_size=2, image_size=8,
                                decode=False, shuffle=False)
    first = next(stream)
    assert isinstance(first, BatchFiles) and len(first.paths) == 2
    rest = list(stream)
    with PrefetchLoader(
            iter([first] + rest), depth=2, workers=2,
            transform=lambda t: (normalize_images(load_batch(t)[0]),
                                 load_batch(t)[1])) as lo:
        out = list(lo)
    assert len(out) == 4
    for x, y in out:
        assert x.shape == (2, 8, 8, 3) and x.dtype == jnp.float32
        assert y.shape == (2,)


def test_stage_windows_multiworker_roundtrip():
    """stage_windows on the multi-worker engine: whole [k, ...] windows
    assembled in the pool, delivered in order with n_valid tails, and
    the transform runs EXACTLY once per source batch (review fix: the
    ragged-tail pad happens after the transform, not before)."""
    import itertools

    from apex_tpu import runtime

    calls = itertools.count()

    def transform(b):
        next(calls)
        return b

    batches = [(np.full((2, 3), i, np.float32), np.int32(i))
               for i in range(7)]
    with runtime.stage_windows(iter(batches), 3, workers=2,
                               depth=2, transform=transform) as lo:
        wins = list(lo)
    assert next(calls) == 7                       # once per source batch
    assert [n for _, n in wins] == [3, 3, 1]      # ragged tail padded
    for j, (win, _) in enumerate(wins):
        assert win[0].shape == (3, 2, 3)
        for s in range(min(3, 7 - 3 * j)):
            assert float(win[0][s, 0, 0]) == 3 * j + s
    # the pad rows replicate the transformed LAST real batch
    assert float(wins[2][0][0][2, 0, 0]) == 6.0
    assert lo.stats.snapshot()["batches"] == 3
    with pytest.raises(ValueError, match="k must be >= 1"):
        runtime.stage_windows(iter(batches), 0)
