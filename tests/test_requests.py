"""Offline per-request analyzer (ISSUE 20 tentpole, piece 4):
``python -m apex_tpu.prof.requests``.

The acceptance pins:

* **percentile math** on hand-built streams is exact (the shared
  nearest-rank definition — the same numbers the engine's reservoirs
  report, which bench.py gates within 2% end to end);
* **waterfall reassembly** orders spans by start and anchors each
  trace on its single ``request`` root;
* **multi-host merge** shifts every host's events onto host 0's clock
  through the fleet alignment path and keeps all requests;
* **CLI e2e** over a real traced engine run: report, ``--json``,
  ``--slo`` goodput, and a ``--chrome`` export with one process lane
  per sampled request;
* **schema/CI**: the timeline analysis is now schema 1.2 with a
  ``requests`` section, and ``prof.regress`` round-trips a 1.1
  artifact against a 1.2 one (minor bump, no future-major refusal).
"""

import json
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models import gpt_tiny
from apex_tpu.prof import regress, timeline
from apex_tpu.prof import requests as prof_requests

VOCAB = 256


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


def _done(t, ttft, tpot, total, queue_wait, n_tokens=4, **extra):
    return {"t": t, "kind": "serving", "phase": "done", "ttft_s": ttft,
            "tpot_s": tpot, "total_s": total, "queue_wait_s": queue_wait,
            "n_tokens": n_tokens, **extra}


def _span(t, name, trace, span, dur, parent=None, **fields):
    e = {"t": t, "kind": "span", "name": name, "trace": trace,
         "span": span, "dur": dur, **fields}
    if parent is not None:
        e["parent"] = parent
    return e


# -- pure analysis ------------------------------------------------------------

def test_request_stats_percentiles_and_batch_join():
    events = [_done(float(i), ttft=0.01 * (i + 1), tpot=0.001,
                    total=0.1, queue_wait=0.0) for i in range(10)]
    events += [{"t": 20.0 + i, "kind": "serving", "phase": "decode",
                "dur": 0.002 * bs, "active": bs}
               for i, bs in enumerate([1, 1, 2, 2, 2, 4])]
    st = prof_requests.request_stats(events)
    assert st["n_requests"] == 10 and st["tokens_out"] == 40
    # nearest-rank over 0.01..0.10: p50 = idx round(4.5) -> 0.05s
    assert st["ttft"]["p50_ms"] == pytest.approx(50.0)
    assert st["ttft"]["p99_ms"] == pytest.approx(100.0)
    assert st["tpot"]["p50_ms"] == pytest.approx(1.0)
    curve = {r["batch_size"]: r for r in st["batch_tpot"]}
    assert curve[1]["steps"] == 2
    assert curve[2]["mean_step_ms"] == pytest.approx(4.0)
    assert curve[4]["steps"] == 1
    # a stream with no serving traffic has no requests section
    assert prof_requests.request_stats(
        [{"t": 0.0, "kind": "window"}]) is None


def test_waterfalls_order_and_root():
    tr = "t0-000000"
    events = [
        # emitted root-after-children order, ends as timestamps:
        _span(1.30, "request", tr, "s0", 1.30),
        _span(0.20, "queue", tr, "s1", 0.20, parent="s0", slot=0),
        _span(0.45, "prefill", tr, "s2", 0.25, parent="s0",
              prompt_len=7),
        _span(0.90, "decode_step", tr, "s3", 0.05, parent="s0",
              batch_size=2),
    ]
    [w] = prof_requests.build_waterfalls(events)
    assert w["trace"] == tr and w["n_spans"] == 4
    assert w["e2e_ms"] == pytest.approx(1300.0)
    assert w["decode_steps"] == 1
    # sorted by start = t - dur: queue (0.0) = request (0.0, longer
    # first loses to equal start? queue dur shorter sorts after) ...
    starts = [r["start_s"] for r in w["spans"]]
    assert starts == sorted(starts)
    assert w["spans"][0]["name"] == "request"     # longest at t=0
    assert w["spans"][1]["name"] == "queue"


def test_analyze_goodput_and_online_summary():
    events = ([_done(float(i), 0.01, 0.001, 0.05, 0.0)
               for i in range(8)]
              + [_done(9.0, 0.9, 0.001, 1.0, 0.0)]
              + [{"t": 10.0, "kind": "summary",
                  "slo": {"goodput_pct": 88.9}}])
    a = prof_requests.analyze(events, slo="ttft_p90<100ms")
    assert a["requests"]["n_requests"] == 9
    assert a["slo"]["good"] == 8
    assert a["slo"]["goodput_pct"] == pytest.approx(100.0 * 8 / 9,
                                                    abs=1e-3)
    assert a["slo_online"]["goodput_pct"] == 88.9
    assert "goodput" in prof_requests.format_report(a)


def test_multihost_merge_onto_host0_clock(tmp_path):
    """Two hosts, anchors 100s apart: the merge lands both hosts'
    requests on host 0's stream clock and keeps every done event."""
    paths = []
    for host, anchor in ((0, 1000.0), (1, 1100.0)):
        p = tmp_path / f"serve_host{host}.jsonl"
        events = [{"t": 0.0, "kind": "run", "run_id": f"r{host}",
                   "process_index": host, "process_count": 2,
                   "anchor_unix": anchor},
                  _done(5.0 + host, 0.01, 0.001, 0.05, 0.0)]
        with open(p, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        paths.append(str(p))
    merged = prof_requests.load_request_events(paths)
    dones = [e for e in merged if e.get("phase") == "done"]
    assert len(dones) == 2
    assert sorted(e["host"] for e in dones) == [0, 1]
    # host 1's done at local t=6 + (1100-1000) anchor delta = 106 on
    # host 0's clock (no window overlap -> no residual skew term)
    t_by_host = {e["host"]: e["t"] for e in dones}
    assert t_by_host[0] == pytest.approx(5.0)
    assert t_by_host[1] == pytest.approx(106.0)
    st = prof_requests.request_stats(merged)
    assert st["n_requests"] == 2


# -- CLI e2e over a real traced run -------------------------------------------

@pytest.fixture(scope="module")
def traced_stream(tmp_path_factory):
    """One real engine load with full sampling + SLO, shared by the
    CLI tests."""
    d = tmp_path_factory.mktemp("requests_cli")
    path = str(d / "serve.jsonl")
    m = gpt_tiny(max_len=64, vocab_size=VOCAB, hidden_size=64,
                 num_layers=2, num_heads=2, mlp_dim=128)
    rs = np.random.RandomState(0)
    probe = jnp.asarray(rs.randint(1, VOCAB, (1, 8)))
    params = m.init(jax.random.PRNGKey(1), probe)["params"]
    rec = telemetry.start(path, watchdog=True, trace_sample_n=1,
                          slo="ttft_p99<60s,tpot_p99<60s")
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, telemetry=rec)
    eng.warmup()
    prompts = [rs.randint(1, VOCAB, (int(n),)).astype(np.int32)
               for n in rs.randint(3, 10, 5)]
    eng.generate(prompts, max_new_tokens=4)
    eng.close()
    rec.close()
    telemetry.set_recorder(None)
    return path


def test_cli_report_and_json(traced_stream, capsys):
    assert prof_requests.main([traced_stream]) == 0
    out = capsys.readouterr().out
    assert "5 finished" in out and "ttft" in out and "trace t0-" in out
    assert prof_requests.main(
        [traced_stream, "--json", "--slo", "ttft_p99<60s"]) == 0
    a = json.loads(capsys.readouterr().out)
    assert a["requests"]["n_requests"] == 5
    assert a["n_sampled"] == 5
    assert a["slo"]["met"] is True
    assert a["slo"]["goodput_pct"] == 100.0
    # every waterfall is a rooted tree with decode steps
    for w in a["waterfalls"]:
        assert w["e2e_ms"] is not None and w["decode_steps"] > 0


def test_cli_chrome_one_lane_per_request(traced_stream, tmp_path):
    out = str(tmp_path / "req.trace.json")
    assert prof_requests.main([traced_stream, "--chrome", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    lanes = {e["pid"] for e in evs}
    assert len(lanes) == 5                        # one pid per request
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert all(n.startswith("req t0-") for n in names)
    # spans became X slices with positive duration
    assert any(e["ph"] == "X" and e.get("dur", 0) > 0 for e in evs)


def test_cli_missing_stream_errors(tmp_path, capsys):
    assert prof_requests.main([str(tmp_path / "nope.jsonl")]) == 1
    assert "error" in capsys.readouterr().err


# -- schema / regress ---------------------------------------------------------

def test_timeline_schema_1_2_requests_section(traced_stream):
    assert timeline.SCHEMA_VERSION == "1.2"
    a = timeline.analyze(timeline.load_events(traced_stream))
    assert a["schema_version"] == "1.2"
    assert a["requests"]["n_requests"] == 5
    assert a["requests"]["ttft"]["p99_ms"] is not None
    assert "serving: 5 requests" in timeline.format_report(a)
    # analyzer agrees with prof.requests on the same stream (identical
    # code path — the bench gates the engine-reservoir side)
    st = prof_requests.request_stats(
        prof_requests.load_request_events([traced_stream]))
    assert a["requests"]["ttft"]["p99_ms"] == st["ttft"]["p99_ms"]


def test_regress_roundtrips_1_1_and_1_2(traced_stream):
    """A 1.1-era summary diffs against a 1.2 one: the minor bump must
    not trip the future-major refusal, the new requests.* latency keys
    are direction-classified, and histogram bucket arrays stay out of
    the diff (lists are not metrics)."""
    cur = timeline.analyze(timeline.load_events(traced_stream))
    base = dict(cur, schema_version="1.1")
    base.pop("requests")
    timeline.check_schema_version(base)
    timeline.check_schema_version(cur)
    d = regress.diff_summaries(base, cur)
    assert d["regressions"] == []                # disjoint keys skip
    # same-schema diff classifies the new latency keys
    d2 = regress.diff_summaries(cur, cur)
    assert d2["regressions"] == []
    flat = regress.flatten_metrics(cur)
    assert any(k.startswith("requests.ttft.") for k in flat)
    assert not any("buckets" in k for k in flat)
    # a FUTURE major still refuses loudly
    with pytest.raises(ValueError, match="FUTURE major"):
        timeline.check_schema_version(dict(cur, schema_version="2.0"))
    # goodput/burn directions (ISSUE 20): a goodput collapse past the
    # tolerance+pct-point slack is a regression (higher-is-better)
    down = {"slo": {"goodput_pct": 50.0}}
    up = {"slo": {"goodput_pct": 99.0}}
    d3 = regress.diff_summaries(up, down)
    assert any(r["metric"] == "slo.goodput_pct"
               for r in d3["regressions"])
