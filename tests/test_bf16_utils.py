"""Legacy bf16_utils/fp16_utils surface tests (reference
tests/L0/run_fp16util/test_fp16util.py pattern: conversion type checks, plus
FP16_Optimizer step/overflow/checkpoint behavior)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import bf16_utils, fp16_utils
from apex_tpu.bf16_utils import (
    BN_convert_float, BF16Model, DynamicLossScaler, FP16_Optimizer,
    clip_grad_norm, convert_network, master_params_to_model_params,
    model_grads_to_master_grads, network_to_half, prep_param_lists, to_bf16)
from apex_tpu.optimizers import FusedSGD


def _params():
    return {
        "dense": {"kernel": jnp.ones((4, 4), jnp.float32),
                  "bias": jnp.zeros((4,), jnp.float32)},
        "bn": {"scale": jnp.ones((4,), jnp.float32),
               "bias": jnp.zeros((4,), jnp.float32)},
    }


def test_fp16_utils_is_alias():
    assert fp16_utils.FP16_Optimizer is bf16_utils.FP16_Optimizer


def test_convert_network_keeps_norm_fp32():
    conv = convert_network(_params(), jnp.bfloat16)
    assert conv["dense"]["kernel"].dtype == jnp.bfloat16
    assert conv["bn"]["scale"].dtype == jnp.float32


def test_bn_convert_float_restores_norm():
    all_bf16 = to_bf16(_params())
    back = BN_convert_float(all_bf16)
    assert back["bn"]["scale"].dtype == jnp.float32
    assert back["dense"]["kernel"].dtype == jnp.bfloat16


def test_network_to_half_casts_inputs():
    def apply_fn(p, x):
        assert x.dtype == jnp.bfloat16
        return x @ p["dense"]["kernel"]

    bf16_apply, p = network_to_half(apply_fn, _params())
    out = bf16_apply(p, jnp.ones((2, 4), jnp.float32))
    assert out.dtype == jnp.bfloat16

    model = BF16Model(apply_fn, _params())
    assert model(jnp.ones((2, 4), jnp.float32)).shape == (2, 4)


def test_prep_param_lists_flat_roundtrip():
    params = to_bf16(_params())
    model_p, master = prep_param_lists(params, flat_master=True)
    assert master.dtype == jnp.float32
    assert master.size == sum(x.size for x in jax.tree_util.tree_leaves(params))
    restored = master_params_to_model_params(model_p, master, flat_master=True)
    chex_leaves = jax.tree_util.tree_leaves(restored)
    for a, b in zip(chex_leaves, jax.tree_util.tree_leaves(params)):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))


def test_master_grads_cast():
    grads = to_bf16({"w": jnp.full((3,), 2.0)})
    master = model_grads_to_master_grads(grads)
    assert master["w"].dtype == jnp.float32


def test_clip_grad_norm():
    grads = {"a": jnp.full((4,), 3.0), "b": jnp.full((9,), 4.0)}
    norm = float(np.sqrt(4 * 9 + 9 * 16))
    clipped, total = clip_grad_norm(grads, norm / 2)
    assert abs(float(total) - norm) < 1e-4
    new_norm = float(jnp.sqrt(sum(jnp.sum(x ** 2)
                                  for x in jax.tree_util.tree_leaves(clipped))))
    assert abs(new_norm - norm / 2) < 1e-3


def test_dynamic_loss_scaler_state_machine():
    s = DynamicLossScaler(init_scale=4.0, scale_window=2)
    assert not s.has_overflow({"g": jnp.ones((2,))})
    assert s.has_overflow({"g": jnp.asarray([1.0, np.inf])})
    s.update_scale(True)
    assert s.loss_scale == 2.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 4.0


def test_fp16_optimizer_step_and_overflow_skip():
    params = to_bf16({"w": jnp.ones((4,), jnp.float32)})
    opt = FP16_Optimizer(FusedSGD(params, lr=0.5),
                         dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 4.0})
    scale = opt.loss_scale
    # grads of the scaled loss: dL/dw = 1 * scale
    grads = {"w": jnp.full((4,), 1.0 * scale, jnp.bfloat16)}
    opt.backward(grads)
    assert not opt.overflow
    opt.step()
    np.testing.assert_allclose(
        np.asarray(opt.master_params["w"]), 0.5, atol=1e-2)
    assert opt.model_params["w"].dtype == jnp.bfloat16

    w_before = np.asarray(opt.master_params["w"]).copy()
    opt.backward({"w": jnp.asarray([np.inf, 1, 1, 1], jnp.bfloat16)})
    assert opt.overflow
    opt.step()  # skipped
    np.testing.assert_array_equal(np.asarray(opt.master_params["w"]), w_before)
    assert opt.loss_scale == scale / 2


def test_fp16_optimizer_state_dict_roundtrip():
    params = to_bf16({"w": jnp.ones((4,), jnp.float32)})
    opt = FP16_Optimizer(FusedSGD(params, lr=0.1, momentum=0.9),
                         dynamic_loss_scale=True)
    g = {"w": jnp.full((4,), opt.loss_scale, jnp.bfloat16)}
    opt.backward(g)
    opt.step()
    sd = opt.state_dict()

    opt2 = FP16_Optimizer(FusedSGD(to_bf16({"w": jnp.zeros((4,))}),
                                   lr=0.1, momentum=0.9),
                          dynamic_loss_scale=True)
    opt2.load_state_dict(sd)
    np.testing.assert_allclose(np.asarray(opt2.master_params["w"]),
                               np.asarray(opt.master_params["w"]))
    assert opt2.loss_scaler.cur_iter == opt.loss_scaler.cur_iter
