"""apex_tpu.parallel.mesh — the unified N-D sharding frontend (ISSUE 12).

The acceptance contracts:

* a DP×FSDP training step on the 8-device CPU mesh matches the existing
  ``zero1(bucketed=True)`` path BITWISE (same seed, 20 steps);
* zero steady-state retraces under ``prof.assert_trace_count`` after
  ``StepPipeline.warmup`` of the sharded step;
* ZeRO-3 per-device param+optimizer-state bytes scale ~1/shard_count;
* ``multiproc.initialize``/``process_identity`` resolve identity from
  the environment, idempotently.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import runtime, training
from apex_tpu.multi_tensor.buckets import Packed
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel import multiproc
from apex_tpu.parallel.zero import zero1, zero1_partition_spec
from apex_tpu.prof import assert_trace_count
from apex_tpu.training import TrainState, make_train_step

NDEV = 8
STEPS = 20


def _setup():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(5, 7) * 0.3, jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}   # 38 elems: pads to 40
    x = jnp.asarray(rng.randn(8 * NDEV, 5), jnp.float32)
    y = jnp.asarray(rng.randn(8 * NDEV, 7) * 0.1, jnp.float32)
    return params, x, y


def _loss_fn(p, batch):
    xb, yb = batch
    pred = xb @ p["w"].astype(jnp.float32) + jnp.pad(
        p["b"].astype(jnp.float32), (0, 4))
    return jnp.mean((pred - yb) ** 2)


def _run_zero1_baseline(steps=STEPS):
    """The pre-mesh path: zero1(bucketed=True) on a flat 8-way axis."""
    mesh = Mesh(np.array(jax.devices("cpu")[:NDEV]), ("data",))
    params, x, y = _setup()
    tx = zero1(training.adam(1e-2), "data", num_shards=NDEV, bucketed=True)
    init_fn, step_fn = make_train_step(_loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       axis_name=("data",),
                                       reduce_grads=False)
    state = init_fn(params)
    spec = TrainState(params=P(),
                      opt_state=zero1_partition_spec(state.opt_state,
                                                     "data"),
                      scaler=P(), model_state=P())
    step = jax.jit(shard_map(step_fn, mesh=mesh,
                             in_specs=(spec, (P("data"), P("data"))),
                             out_specs=(spec, P())))
    losses = []
    for _ in range(steps):
        state, m = step(state, (x, y))
        losses.append(float(jnp.ravel(m["loss"])[0]))
    return np.asarray(losses), jax.device_get(state.params)


def _run_mesh(zero, dp, fsdp, steps=STEPS):
    params, x, y = _setup()
    plan = M.MeshPlan(dp=dp, fsdp=fsdp,
                      devices=jax.devices("cpu")[:dp * fsdp])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                zero=zero, opt_level="O2",
                                loss_scale="dynamic")
    state = ms.init(params)
    step = ms.jit_step(state, donate=False)
    batch = plan.device_put_batch((x, y))
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(jnp.ravel(m["loss"])[0]))
    return (np.asarray(losses), jax.device_get(ms.gather_params(state)),
            state, ms, plan)


# -- plan declaration ---------------------------------------------------------

def test_plan_validates_sizes_and_derives_axes():
    devs = jax.devices("cpu")[:8]
    plan = M.MeshPlan(dp=2, fsdp=4, devices=devs)
    assert plan.world_size == 8 and plan.data_world == 8
    assert plan.data_axes == ("dp", "fsdp")
    assert plan.mesh.shape == {"dp": 2, "fsdp": 4, "tp": 1}
    assert "dp=2" in repr(plan)
    with pytest.raises(ValueError, match="dp\\*fsdp\\*tp"):
        M.MeshPlan(dp=3, fsdp=4, devices=devs)
    with pytest.raises(ValueError, match=">= 1"):
        M.MeshPlan(dp=0, fsdp=8, devices=devs)


def test_plan_auto_fills_dp():
    devs = jax.devices("cpu")[:8]
    plan = M.MeshPlan.auto(devices=devs)          # pure FSDP default
    assert (plan.dp, plan.fsdp, plan.tp) == (1, 8, 1)
    plan = M.MeshPlan.auto(fsdp=4, devices=devs)
    assert (plan.dp, plan.fsdp) == (2, 4)


def test_plan_derived_shardings_agree():
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
    assert plan.batch_spec == P(("dp", "fsdp"))
    assert plan.flat_spec == P("fsdp")
    x = jnp.arange(16.0).reshape(16, 1)
    placed = plan.device_put_batch(x)
    assert placed.sharding == plan.batch_sharding()
    assert placed.committed                       # warmup can pin it


# -- bitwise parity with the pre-mesh zero1 path (acceptance) -----------------

@pytest.mark.parametrize("zero,dp,fsdp", [(2, 2, 4), (3, 2, 4), (3, 1, 8)])
def test_mesh_zero_matches_zero1_bitwise(zero, dp, fsdp):
    """DP×FSDP on the 8-device CPU mesh, 20 steps, same seed: losses
    AND final params bitwise-equal to zero1(bucketed=True) — the mesh
    frontend is a re-plumbing, not a renumbering."""
    base_losses, base_params = _run_zero1_baseline()
    losses, params, state, ms, plan = _run_mesh(zero, dp, fsdp)
    np.testing.assert_array_equal(base_losses, losses)
    for k in base_params:
        np.testing.assert_array_equal(np.asarray(base_params[k]),
                                      np.asarray(params[k]))
    assert losses[-1] < losses[0]


def test_zero3_state_is_actually_sharded():
    """ZeRO-3 per-device param+optimizer-state bytes ~ 1/shard_count."""
    _, _, state, ms, plan = _run_mesh(3, 1, 8, steps=1)
    led = plan.state_bytes((state.params, state.opt_state))
    # 8-way sharding: one device holds ~1/8 of the flat buckets (the
    # scaler scalars and step counters stay replicated, hence ~)
    assert led["ratio"] <= 1.0 / 8 + 0.05, led
    # and the flat buckets really carry the fsdp sharding
    for b in state.params.data:
        assert b.sharding == plan.flat_sharding()
        shard = b.sharding.shard_shape(b.shape)
        assert shard[0] == b.shape[0] // 8


def test_zero2_params_stay_replicated_state_sharded():
    _, _, state, ms, plan = _run_mesh(2, 2, 4, steps=1)
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.sharding.is_fully_replicated
    led = plan.state_bytes(state.opt_state)
    assert led["ratio"] <= 1.0 / 4 + 0.1, led


def test_zero3_pipeline_warmup_zero_retraces():
    """The sharded step through StepPipeline: AOT warmup, then ZERO
    traces for the whole run (acceptance), trajectory bitwise equal to
    the per-step zero1 baseline."""
    K = 4
    base_losses, _ = _run_zero1_baseline(steps=3 * K)
    params, x, y = _setup()
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                zero=3, opt_level="O2",
                                loss_scale="dynamic")
    state = ms.init(params)
    pipe = runtime.StepPipeline(ms.step_fn, K,
                                wrap=ms.pipeline_wrap(state))

    def window():
        w = jax.tree_util.tree_map(
            lambda a: np.broadcast_to(np.asarray(a), (K,) + a.shape),
            (x, y))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, plan.window_sharding()), w)

    pipe.warmup(state, window())
    losses = []
    with assert_trace_count(pipe.loop, 0):
        for _ in range(3):
            state, metrics = pipe.step_window(state, window(), K)
            losses += [float(v) for v in
                       np.ravel(jax.device_get(metrics["loss"]))]
    np.testing.assert_array_equal(base_losses, np.asarray(losses))


def test_zero3_overflow_on_one_shard_skips_everywhere():
    """One fsdp shard's inf grads must skip the step on EVERY rank —
    the mesh-wide overflow agreement zero1 pioneered, across BOTH axes."""
    params, x, y = _setup()
    x = np.array(x)
    x[0, 0] = np.inf                              # shard (dp=0, fsdp=0)
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                zero=3, opt_level="O2",
                                loss_scale="dynamic")
    state = ms.init(params)
    step = ms.jit_step(state, donate=False)
    state1, m = step(state, plan.device_put_batch((jnp.asarray(x), y)))
    # params untouched (global skip), moments finite, scale halved
    p0 = ms.gather_params(state)
    p1 = ms.gather_params(state1)
    for k in p0:
        np.testing.assert_array_equal(np.asarray(p0[k]), np.asarray(p1[k]))
    for leaf in jax.tree_util.tree_leaves(state1.opt_state):
        assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
    assert float(state1.scaler.loss_scale) == 2.0 ** 15


@pytest.mark.parametrize("zero", [2, 3])
def test_decay_mask_and_buckets_forwarded(zero):
    """Regression: make_mesh_train_step used to drop max_bucket_elems /
    decay_mask on the zero<3 path, and neither level zeroed
    weight_decay on the no-decay buckets a mask splits off."""
    params, x, y = _setup()

    def run(weight_decay, decay_mask):
        plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
        ms = M.make_mesh_train_step(
            _loss_fn, training.adam(1e-2, weight_decay=weight_decay),
            plan, zero=zero, opt_level="O0", decay_mask=decay_mask,
            max_bucket_elems=16)
        state = ms.init(params)
        step = ms.jit_step(state, donate=False)
        batch = plan.device_put_batch((x, y))
        for _ in range(3):      # b leaves its zero init, so decay bites
            state, _ = step(state, batch)
        return jax.device_get(ms.gather_params(state)), state

    all_off, _ = run(0.5, {"w": False, "b": False})
    no_wd, _ = run(0.0, None)
    decayed, dstate = run(0.5, None)
    # an all-False mask must neutralize weight_decay exactly — bitwise
    # equal to the weight_decay=0 run (same bucket chunking, same math)
    for k in params:
        np.testing.assert_array_equal(np.asarray(all_off[k]),
                                      np.asarray(no_wd[k]))
    # and without the mask, decay genuinely moves every leaf
    # (b leaves its zero init at step 1, so steps 2-3 decay it too)
    for k in params:
        assert not np.array_equal(np.asarray(all_off[k]),
                                  np.asarray(decayed[k])), k
    # max_bucket_elems reached the store: even without a mask, w (35
    # elems) and b (3) can't share one 16-cap bucket, so the optimizer
    # state is multi-bucket
    assert len(dstate.opt_state.inner) >= 2


def test_zero3_accum_steps_applies_view_transpose():
    """Regression: accum_steps>1 with ZeRO-3 used to crash at trace
    time (the hoisted compute cast dropped the param_view, so the
    accumulated grads came back in the full-tree layout).  The view is
    now hoisted via jax.vjp — one gather per step, its transpose (the
    reduce-scatter) applied once to the accumulated gradient — so the
    trajectory matches the unaccumulated step."""
    params, x, y = _setup()
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])

    def run(accum_steps, steps=5):
        ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                    zero=3, opt_level="O2",
                                    loss_scale="dynamic",
                                    accum_steps=accum_steps)
        state = ms.init(params)
        step = ms.jit_step(state, donate=False)
        batch = plan.device_put_batch((x, y))
        losses = []
        for _ in range(steps):
            state, m = step(state, batch)
            losses.append(float(jnp.ravel(m["loss"])[0]))
        return np.asarray(losses), jax.device_get(ms.gather_params(state))

    base_losses, base_params = run(1, steps=1)
    acc_losses, acc_params = run(2, steps=1)
    # mean-reduced MSE is batch-size invariant: after ONE step only the
    # float reassociation of the microbatch mean separates the runs —
    # a missing/wrong view transpose would be off by the gather factor
    np.testing.assert_allclose(acc_losses, base_losses, rtol=1e-5)
    for k in base_params:
        np.testing.assert_allclose(np.asarray(acc_params[k]),
                                   np.asarray(base_params[k]),
                                   rtol=1e-5, atol=1e-6)
    # and the accumulated trajectory keeps training (adam amplifies the
    # reassociation noise over steps, so no bitwise pin here)
    acc_losses, _ = run(2, steps=5)
    assert np.all(np.isfinite(acc_losses)) and acc_losses[-1] < acc_losses[0]


# -- ZeRO-3 bf16 gather (ISSUE 13 satellite) ----------------------------------

def _run_mesh_gather(gather_dtype, steps=STEPS):
    params, x, y = _setup()
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                zero=3, opt_level="O2",
                                loss_scale="dynamic",
                                gather_dtype=gather_dtype)
    state = ms.init(params)
    step = ms.jit_step(state, donate=False)
    batch = plan.device_put_batch((x, y))
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(jnp.ravel(m["loss"])[0]))
    return np.asarray(losses), jax.device_get(ms.gather_params(state))


def test_zero3_fp32_gather_path_stays_bitwise():
    """gather_dtype=None (the default) is the exact pre-existing wire:
    bitwise-equal to the zero1(bucketed=True) baseline."""
    base_losses, base_params = _run_zero1_baseline()
    losses, params = _run_mesh_gather(None)
    np.testing.assert_array_equal(base_losses, losses)
    for k in base_params:
        np.testing.assert_array_equal(np.asarray(base_params[k]),
                                      np.asarray(params[k]))


def test_zero3_bf16_gather_tracks_fp32():
    """The bf16 wire halves gather/scatter bytes; under O2 the compute
    cast was shipping bf16 into the matmuls anyway, so the trajectory
    TRACKS the fp32-wire run (tolerance, not bitwise — the weight
    rounding moves one op earlier) and still learns."""
    ref_losses, ref_params = _run_mesh_gather(None)
    losses, params = _run_mesh_gather(jnp.bfloat16)
    assert losses[-1] < losses[0]
    np.testing.assert_allclose(losses, ref_losses, rtol=0.05, atol=5e-3)
    for k in ref_params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=0.05, atol=5e-3)


def test_zero3_bf16_gather_halves_wire_bytes(tmp_path):
    """Per-axis collective-bytes assertion: the fsdp all_gather AND its
    transpose reduce_scatter are noted with bf16 dtype at HALF the
    fp32 run's bytes; the dp psum is untouched."""
    import json

    from apex_tpu import telemetry

    def collect(gather_dtype, path):
        params, x, y = _setup()
        plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
        ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                    zero=3, opt_level="O2",
                                    gather_dtype=gather_dtype)
        rec = telemetry.start(path)
        try:
            state = ms.init(params)
            step = ms.jit_step(state, donate=False)
            state, m = step(state, plan.device_put_batch((x, y)))
            jax.block_until_ready(m["loss"])
        finally:
            rec.close()
        events = [json.loads(l) for l in open(path) if l.strip()]
        out = {}
        for e in events:
            if e.get("kind") != "collective":
                continue
            key = (e["op"], e["axis"] if isinstance(e["axis"], str)
                   else tuple(e["axis"]))
            out[key] = out.get(key, 0) + e["bytes"] * e["n"]
        dts = {e.get("dtype") for e in events
               if e.get("kind") == "collective"
               and e.get("op") in ("all_gather", "reduce_scatter")}
        return out, dts

    fp32, dt32 = collect(None, str(tmp_path / "fp32.jsonl"))
    bf16, dt16 = collect(jnp.bfloat16, str(tmp_path / "bf16.jsonl"))
    assert bf16[("all_gather", "fsdp")] * 2 == fp32[("all_gather", "fsdp")]
    assert (bf16[("reduce_scatter", "fsdp")] * 2
            == fp32[("reduce_scatter", "fsdp")])
    assert bf16[("psum", "dp")] == fp32[("psum", "dp")]
    assert dt32 == {"float32"} and dt16 == {"bfloat16"}


def test_gather_dtype_rejected_below_zero3():
    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])
    with pytest.raises(ValueError, match="gather_dtype"):
        M.make_mesh_train_step(_loss_fn, training.adam(1e-3), plan,
                               zero=2, gather_dtype=jnp.bfloat16)


# -- contracts & rejections ---------------------------------------------------

def test_zero_sharded_rejects_per_tensor_norm_optimizers():
    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])
    with pytest.raises(ValueError, match="elementwise"):
        M.zero_sharded(training.lamb(1e-3), plan, level=2)
    with pytest.raises(ValueError, match="elementwise"):
        M.zero_sharded(training.novograd(1e-3), plan, level=3)
    with pytest.raises(ValueError, match="level"):
        M.zero_sharded(training.adam(1e-3), plan, level=4)
    with pytest.raises(ValueError, match="level"):
        # regression: an out-of-range level must not fall through to
        # the zero-3 branch of the frontend
        M.make_mesh_train_step(_loss_fn, training.adam(1e-3), plan,
                               zero=5)


def test_zero3_rejects_reduced_precision_storage():
    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])
    with pytest.raises(ValueError, match="fp32 flat buckets"):
        M.make_mesh_train_step(_loss_fn, training.adam(1e-3), plan,
                               zero=3, opt_level="O3")


def test_zero3_step_before_init_raises():
    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-3), plan,
                                zero=3)
    with pytest.raises(RuntimeError, match="init"):
        ms.step_fn(None, None)
    with pytest.raises(RuntimeError, match="init"):
        ms.store()


def test_zero3_store_and_bucket_layout_for_checkpoints():
    params, _, _ = _setup()
    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-3), plan,
                                zero=3)
    state = ms.init(params)
    store = ms.store()
    layout = plan.bucket_layout(store)
    assert layout == {"sizes": [38], "num_shards": 8}
    assert isinstance(state.params, Packed)
    assert state.params.data[0].shape == (40,)    # padded_shard_len(38, 8)


# -- multiproc: identity & launch ---------------------------------------------

def test_multiproc_identity_from_env(monkeypatch):
    monkeypatch.setenv("RANK", "3")
    monkeypatch.setenv("WORLD_SIZE", "4")
    assert multiproc.process_identity() == (3, 4)
    assert not multiproc.is_coordinator()
    monkeypatch.setenv("RANK", "0")
    assert multiproc.is_coordinator()
    # jax-native spellings win over torchrun's
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    assert multiproc.process_identity() == (1, 4)


def test_multiproc_identity_rejects_out_of_range(monkeypatch):
    monkeypatch.setenv("RANK", "7")
    monkeypatch.setenv("WORLD_SIZE", "4")
    with pytest.raises(ValueError, match="not in"):
        multiproc.process_identity()


def test_multiproc_single_process_initialize_is_noop_and_idempotent(
        monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    saved = dict(multiproc._STATE)
    try:
        multiproc._STATE.update(initialized=False, procs=None)
        assert multiproc.initialize() == (0, 1)
        assert multiproc.initialize() == (0, 1)   # idempotent
        assert multiproc.process_identity() == (0, 1)
        assert multiproc.is_coordinator()
    finally:
        multiproc._STATE.update(saved)


def test_multiproc_worker_env_round_trips():
    env = multiproc.worker_env(1, 2, "127.0.0.1:9999", base={})
    assert env["JAX_PROCESS_ID"] == "1" and env["RANK"] == "1"
    assert env["JAX_NUM_PROCESSES"] == "2" and env["WORLD_SIZE"] == "2"
    assert env["JAX_COORDINATOR_ADDRESS"] == "127.0.0.1:9999"


def test_checkpoint_manager_adopts_multiproc_identity(tmp_path,
                                                      monkeypatch):
    """The ISSUE 12 satellite: a spawned worker's CheckpointManager
    shards by the LAUNCHER env even before jax.distributed is up."""
    from apex_tpu.checkpoint import CheckpointManager

    monkeypatch.setenv("RANK", "1")
    monkeypatch.setenv("WORLD_SIZE", "2")
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.procs == (1, 2)
    mgr.close()


def test_telemetry_recorder_stamps_multiproc_identity(tmp_path,
                                                      monkeypatch):
    import json

    from apex_tpu import telemetry

    monkeypatch.setenv("RANK", "1")
    monkeypatch.setenv("WORLD_SIZE", "2")
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    rec.close()
    run_ev = [json.loads(l) for l in open(path) if l.strip()][0]
    assert run_ev["process_index"] == 1
    assert run_ev["process_count"] == 2


@pytest.mark.slow
def test_real_two_process_multihost_smoke():
    """The full multi-host gate: 2 REAL processes, gloo collectives,
    bitwise cross-host parity, per-host checkpoint shards, fleet merge
    (also run by docker/run_matrix.sh and bench.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "multihost_smoke.py"), "--nproc", "2"],
        capture_output=True, text=True, timeout=570)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_mesh_collectives_note_their_axis(tmp_path):
    """The ZeRO-3 step's trace-time collective events carry the mesh
    axis they cross — fsdp for the param gather/grad scatter, dp for
    the replica psum — so fleet/timeline attribution can split them."""
    import json

    from apex_tpu import telemetry

    params, x, y = _setup()
    plan = M.MeshPlan(dp=2, fsdp=4, devices=jax.devices("cpu")[:8])
    ms = M.make_mesh_train_step(_loss_fn, training.adam(1e-2), plan,
                                zero=3, opt_level="O2")
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    try:
        state = ms.init(params)
        step = ms.jit_step(state, donate=False)
        state, m = step(state, plan.device_put_batch((x, y)))
        jax.block_until_ready(m["loss"])
    finally:
        rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    colls = [e for e in events if e.get("kind") == "collective"]
    by_op = {}
    for e in colls:
        by_op.setdefault(e["op"], set()).add(
            e["axis"] if isinstance(e["axis"], str)
            else tuple(e["axis"]))
    assert "fsdp" in by_op.get("all_gather", set())
    assert "fsdp" in by_op.get("reduce_scatter", set())
    assert "dp" in by_op.get("psum", set())
