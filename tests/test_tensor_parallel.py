"""Tensor-parallel layers: sharded compute == unsharded oracle.

Beyond-parity (reference is DP-only, SURVEY.md §2.10): Megatron-style
column/row-parallel matmuls over a mesh axis, validated on the virtual
CPU mesh the way the reference validates SyncBN against the whole-batch
oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import (column_parallel_dense, row_parallel_dense,
                               shard_column, shard_row, tp_mlp,
                               tp_self_attention)

# Pre-vma jax (< 0.5; conftest shims shard_map with check_rep=False)
# inserts no implicit psum when differentiating w.r.t. replicated params
# under shard_map, so grad-vs-sequential-oracle comparisons only hold on
# vma-aware jax.
_pre_vma_jax = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="asserts jax>=0.5 shard_map autodiff (implicit psum) semantics")


@pytest.fixture
def tp_mesh():
    return Mesh(np.array(jax.devices("cpu")[:4]), ("tp",))


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape) * 0.1,
                       jnp.float32)


def test_column_row_pair_matches_dense(tp_mesh):
    x = _rand((8, 64), 0)
    w1 = _rand((64, 128), 1)
    b1 = _rand((128,), 2)
    w2 = _rand((128, 64), 3)
    b2 = _rand((64,), 4)

    def sharded(x, w1, b1, w2, b2):
        h = column_parallel_dense(x, w1, b1)
        return row_parallel_dense(h, w2, "tp", b=b2)

    y = jax.jit(shard_map(
        sharded, mesh=tp_mesh,
        in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None), P()),
        out_specs=P()))(x, w1, b1, w2, b2)
    ref = (x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_tp_mlp_matches_dense(tp_mesh):
    x = _rand((4, 16, 64), 0)
    w1, b1 = _rand((64, 256), 1), _rand((256,), 2)
    w2, b2 = _rand((256, 64), 3), _rand((64,), 4)

    y = jax.jit(shard_map(
        lambda x, w1, b1, w2, b2: tp_mlp(x, w1, b1, w2, b2, "tp"),
        mesh=tp_mesh,
        in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None), P()),
        out_specs=P()))(x, w1, b1, w2, b2)
    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_tp_self_attention_matches_dense(tp_mesh):
    from apex_tpu.ops.attention import blockwise_attention

    B, T, D, H, E = 2, 16, 32, 4, 8
    x = _rand((B, T, D), 0)
    wqkv = _rand((D, 3, H, E), 1)
    wo = _rand((H * E, D), 2)

    def sharded(x, wqkv, wo):
        return tp_self_attention(x, wqkv, wo, H // 4, "tp", causal=True)

    y = jax.jit(shard_map(
        sharded, mesh=tp_mesh,
        in_specs=(P(), P(None, None, "tp"), P("tp", None)),
        out_specs=P()))(x, wqkv, wo)

    qkv = jnp.einsum("btd,dche->btche", x, wqkv)
    ctx = blockwise_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                              causal=True)
    ref = ctx.reshape(B, T, -1) @ wo
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_shard_helpers_roundtrip(tp_mesh):
    w = _rand((32, 64), 5)

    def get_col(w):
        return shard_column(w, "tp")

    cols = jax.jit(shard_map(get_col, mesh=tp_mesh, in_specs=(P(),),
                             out_specs=P("tp")))(w)
    # gathering the shards along the split axis reconstructs w
    np.testing.assert_array_equal(
        np.asarray(cols).reshape(4, 32, 16).transpose(1, 0, 2).reshape(32, 64),
        np.asarray(w))

    def get_row(w):
        return shard_row(w, "tp")

    rows = jax.jit(shard_map(get_row, mesh=tp_mesh, in_specs=(P(),),
                             out_specs=P("tp")))(w)
    np.testing.assert_array_equal(np.asarray(rows), np.asarray(w))


@_pre_vma_jax
def test_tp_gradients_stay_local_and_match(tp_mesh):
    """Backprop through a column->row pair: each shard's weight grads equal
    the corresponding slice of the dense-model grads (no collective needed
    for TP weight grads — the Megatron property)."""
    x = _rand((8, 64), 0)
    w1 = _rand((64, 128), 1)
    w2 = _rand((128, 64), 3)

    def loss_sharded(x, w1, w2):
        h = column_parallel_dense(x, w1)
        y = row_parallel_dense(h, w2, "tp")
        return jnp.sum(y ** 2) / y.size

    def run(x, w1, w2):
        return jax.grad(loss_sharded, argnums=(1, 2))(x, w1, w2)

    g1, g2 = jax.jit(shard_map(
        run, mesh=tp_mesh,
        in_specs=(P(), P(None, "tp"), P("tp", None)),
        out_specs=(P(None, "tp"), P("tp", None))))(x, w1, w2)

    def loss_dense(x, w1, w2):
        y = (x @ w1) @ w2
        return jnp.sum(y ** 2) / y.size

    r1, r2 = jax.grad(loss_dense, argnums=(1, 2))(x, w1, w2)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(r1),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(r2),
                               atol=1e-5, rtol=1e-5)


def test_tp_self_attention_default_is_flash(tp_mesh):
    """The default attention_fn now routes through flash_attention
    (VERDICT r2 weak #3); off-TPU it computes the identical blockwise
    math, so the dense-reference equivalence must keep holding with NO
    explicit attention_fn."""
    import sys

    import apex_tpu.ops.flash_attention  # noqa: F401
    fa = sys.modules["apex_tpu.ops.flash_attention"]

    rng = np.random.RandomState(5)
    B, T, d, H, hd = 2, 16, 32, 4, 8
    x = jnp.asarray(rng.randn(B, T, d) * .5, jnp.float32)
    wqkv = jnp.asarray(rng.randn(d, 3, H, hd) * .2, jnp.float32)
    wo = jnp.asarray(rng.randn(H * hd, d) * .2, jnp.float32)

    calls = []
    orig = fa.flash_attention

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    fa.flash_attention = spy
    try:
        def local(x, wqkv_l, wo_l):
            return tp_self_attention(x, wqkv_l, wo_l, H // 4, "tp",
                                     causal=True)

        f = shard_map(local, mesh=tp_mesh,
                      in_specs=(P(), P(None, None, "tp"), P("tp")),
                      out_specs=P())
        out = jax.jit(f)(x, wqkv, wo)
    finally:
        fa.flash_attention = orig
    assert calls       # the default path went through flash_attention
    # reference: full-head attention + dense out-proj
    qkv = jnp.einsum("btd,dche->btche", x, wqkv)
    from apex_tpu.ops.attention import dot_product_attention
    ctx = dot_product_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                                causal=True)
    ref = ctx.reshape(B, T, -1) @ wo
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
