"""Flash-attention kernel parity vs the jnp oracle.

The CPU tests run the Pallas kernels in interpreter mode (same kernel
code path as on chip, minus Mosaic lowering); the ``tpu``-marked
counterparts in ``test_pallas_tpu.py`` execute the compiled kernels.
Mirrors the fallback-vs-kernel strategy of the reference's L0 kernel
tests (``tests/L0/run_fused_layer_norm``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import dot_product_attention
from apex_tpu.ops.flash_attention import _pick_block, flash_attention


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_interpret_matches_oracle(causal):
    B, T, H, D = 2, 256, 4, 64
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_interpret_key_padding_bias():
    B, T, H, D = 2, 256, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    valid = jnp.arange(T)[None, :] < jnp.array([200, 64])[:, None]
    kb = jnp.where(valid, 0.0, -1e9)
    out = flash_attention(q, k, v, key_padding_bias=kb, block_q=128,
                          block_k=128, interpret=True)
    ref = dot_product_attention(q, k, v, bias=kb[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_interpret_grads_match_oracle(causal):
    B, T, H, D = 1, 256, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=128, block_k=128, interpret=True))
    ref = loss(lambda q, k, v: dot_product_attention(q, k, v, causal=causal))
    g1 = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_interpret_grads_with_bias():
    B, T, H, D = 1, 128, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    valid = jnp.arange(T)[None, :] < 100
    kb = jnp.where(valid, 0.0, -1e9) * jnp.ones((B, 1))

    # Soft (finite) bias so the bias gradient is non-trivially nonzero.
    kb_soft = jnp.asarray(np.random.RandomState(9).randn(B, T), jnp.float32)

    def f_flash(q, k, v, bias):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, key_padding_bias=bias, block_q=128, block_k=128,
            interpret=True)))

    def f_ref(q, k, v, bias):
        return jnp.sum(jnp.sin(dot_product_attention(
            q, k, v, bias=bias[:, None, None, :])))

    for bias in (kb, kb_soft):
        g1 = jax.grad(f_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        assert float(jnp.linalg.norm(g2[3])) > 0 or bias is kb
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)


def test_flash_fallback_off_tpu_non_tiling_seq():
    # T=100 is not sublane-aligned → jnp blockwise fallback on ANY backend
    # (_pick_block returns None), asserted numerically here.
    B, T, H, D = 2, 100, 2, 16
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pick_block():
    assert _pick_block(128, 512) == 128      # t <= preferred, aligned → t
    assert _pick_block(104, 512) == 104      # sublane-aligned whole-array
    assert _pick_block(100, 512) is None     # unaligned → jnp fallback
    assert _pick_block(1024, 512) == 512     # divides
    assert _pick_block(768, 512) == 384      # largest 128-multiple divisor
    assert _pick_block(640, 512) == 128
    assert _pick_block(1000, 512) is None    # no 128-multiple divides


@pytest.mark.slow
def test_bert_flash_impl_matches_full_off_tpu():
    """attention_impl='flash' (fallback path off-TPU) == 'full' oracle."""
    from apex_tpu.models import bert_tiny

    ids = jnp.asarray(np.random.RandomState(0).randint(0, 1024, (2, 64)))
    m_full = bert_tiny(num_classes=None)
    m_flash = bert_tiny(num_classes=None, attention_impl="flash")
    params = m_full.init(jax.random.PRNGKey(0), ids)
    out_full = m_full.apply(params, ids)
    out_flash = m_flash.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out_flash),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("shape,blocks", [
    ((1, 136, 1, 32), (136, 136)),    # sublane-only alignment (17*8), 1 head
    pytest.param((3, 384, 5, 64), (128, 256),   # mismatched bq/bk, odd
                 marks=pytest.mark.slow),        # head count (slowest case)
    ((2, 256, 2, 128), (256, 128)),   # wide head_dim
    ((1, 512, 3, 16), (512, 128)),    # narrow head_dim, whole-seq q block
])
def test_flash_interpret_fuzz_shapes(shape, blocks):
    """Chunk-boundary style fuzzing (the reference's multi-tensor fuzz
    strategy applied to the attention kernel): odd head counts, sublane-
    only sequence alignment, asymmetric block sizes, extreme head dims —
    fwd AND grads vs the oracle."""
    B, T, H, D = shape
    bq, bk = blocks
    q, k, v = (_rand(shape, s + 10) for s in range(3))

    out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk,
                          interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.cos(fn(q, k, v)))

    g1 = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=bq, block_k=bk, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: dot_product_attention(
        q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_flash_interpret_inf_inputs_propagate():
    """Non-finite Q rows must surface as non-finite outputs (the amp
    overflow machinery depends on inf/nan propagating, reference
    multi-tensor inf/NaN-injection strategy)."""
    B, T, H, D = 1, 128, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    q = q.at[0, 5, 0, :].set(np.inf)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    assert not np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_flash_interpret_2d_bias_fwd_and_grads(causal):
    """[B, T, S] head-broadcast additive bias (segment masks, relative
    position biases) on the kernel path — fwd + all four grads vs the
    oracle, incl. the head-summed dbias from the dedicated kernel."""
    B, T, H, D = 2, 256, 3, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    rng = np.random.RandomState(7)
    seg = jnp.asarray(rng.randint(0, 2, (B, T)))
    hard = jnp.where(seg[:, :, None] == seg[:, None, :], 0.0,
                     -1e30).astype(jnp.float32)
    soft = jnp.asarray(rng.randn(B, T, T), jnp.float32)

    def f_flash(q, k, v, bias):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, bias=bias, block_q=128, block_k=128,
            interpret=True)))

    def f_ref(q, k, v, bias):
        return jnp.sum(jnp.sin(dot_product_attention(
            q, k, v, causal=causal, bias=bias[:, None])))

    for bias in (hard, soft):
        np.testing.assert_allclose(
            float(f_flash(q, k, v, bias)), float(f_ref(q, k, v, bias)),
            atol=1e-4, rtol=1e-4)
        g1 = jax.grad(f_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        if bias is soft:
            assert float(jnp.linalg.norm(g2[3])) > 0
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)


def test_flash_2d_bias_combines_with_key_padding():
    """bias= and key_padding_bias= together fold into one additive term."""
    B, T, H, D = 1, 128, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    rng = np.random.RandomState(3)
    b2 = jnp.asarray(rng.randn(B, T, T), jnp.float32)
    kb = jnp.asarray(rng.randn(B, T), jnp.float32)

    out = flash_attention(q, k, v, bias=b2, key_padding_bias=kb,
                          block_q=128, block_k=128, interpret=True)
    ref = dot_product_attention(
        q, k, v, bias=(b2 + kb[:, None, :])[:, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_per_head_bias_falls_back_to_jnp():
    """[B, H, T, S] per-head bias: no kernel support, documented jnp
    fallback computes the same function; 5-D shapes are rejected."""
    B, T, H, D = 1, 128, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    b4 = jnp.asarray(np.random.RandomState(1).randn(B, H, T, T) * .3,
                     jnp.float32)
    out = flash_attention(q, k, v, bias=b4, interpret=True)
    ref = dot_product_attention(q, k, v, bias=b4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    with pytest.raises(ValueError, match="bias must be"):
        flash_attention(q, k, v, bias=jnp.zeros((B, H, T, T, 1)),
                        interpret=True)


def test_flash_broadcastable_3d_bias():
    """[B,1,S] broadcastable bias is materialized for the kernel path and
    its gradient folds back to the caller's shape; incompatible shapes
    raise loudly instead of reading clamped garbage."""
    B, T, H, D = 1, 256, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    nb = jnp.asarray(np.random.RandomState(2).randn(B, 1, T), jnp.float32)

    out = flash_attention(q, k, v, bias=nb, block_q=128, block_k=128,
                          interpret=True)
    ref = dot_product_attention(q, k, v, bias=nb[:, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    g = jax.grad(lambda b: jnp.sum(flash_attention(
        q, k, v, bias=b, block_q=128, block_k=128, interpret=True) ** 2))(nb)
    gr = jax.grad(lambda b: jnp.sum(dot_product_attention(
        q, k, v, bias=b[:, None]) ** 2))(nb)
    assert g.shape == nb.shape
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               atol=5e-4, rtol=5e-4)

    with pytest.raises(ValueError, match="not broadcastable"):
        flash_attention(q, k, v, bias=jnp.zeros((B, 3, T)), interpret=True)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_kv", [1, 2])
def test_flash_gqa_interpret_matches_repeat_oracle(causal, n_kv):
    """Grouped-query / multi-query attention: kv heads shared across
    query heads through the kernel index maps must equal the repeat-KV
    oracle, fwd + grads (dk/dv come back at kv-head shape, the group-sum
    of the repeated oracle's grads)."""
    B, T, H, D = 2, 256, 4, 32
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, n_kv, D), 1)
    v = _rand((B, T, n_kv, D), 2)

    def f(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=128,
                               block_k=128, interpret=True)

    def ref(q, k, v):
        kr = jnp.repeat(k, H // n_kv, axis=2)
        vr = jnp.repeat(v, H // n_kv, axis=2)
        return dot_product_attention(q, kr, vr, causal=causal)

    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda *a: jnp.sum(jnp.sin(f(*a))), argnums=(0, 1, 2))(
        q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))), argnums=(0, 1, 2))(
        q, k, v)
    assert g1[1].shape == k.shape and g1[2].shape == v.shape
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_gqa_rejects_nondivisible_heads():
    q = _rand((1, 128, 4, 16), 0)
    kv = _rand((1, 128, 3, 16), 1)
    with pytest.raises(ValueError, match="kv heads"):
        flash_attention(q, kv, kv, interpret=True)


@pytest.mark.slow
def test_gpt_gqa_forward_and_train():
    """GPT with num_kv_heads (llama-style GQA) trains end-to-end off-TPU
    (flash fallback repeats KV); kv projections carry fewer heads."""
    from apex_tpu.models import gpt_tiny

    model = gpt_tiny(num_kv_heads=2)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 1024, (2, 64)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    kshape = params["block_0"]["attention"]["key"]["kernel"].shape
    qshape = params["block_0"]["attention"]["query"]["kernel"].shape
    assert kshape[1] == 2 and qshape[1] == 4
    out = model.apply({"params": params}, ids)
    assert out.shape == (2, 64, 1024) and np.isfinite(np.asarray(out)).all()

    # one real amp-O2 train step: grads flow through the kv-head-shaped
    # projections and the repeated-KV fallback, loss decreases over steps
    from apex_tpu import training
    from apex_tpu.training import make_train_step

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch)
        logp = jax.nn.log_softmax(logits[:, :-1])
        tgt = batch[:, 1:]
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-3),
                                       opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    losses = []
    for _ in range(8):
        state, m = step(state, ids)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("window", [1, 100, 150, 256, 511])
def test_flash_sliding_window_matches_band_oracle(window):
    """Sliding-window local attention (bounded kernel grid: only
    ceil(w/bk)+1 KV blocks per Q block are visited) vs the full-attention
    oracle with an explicit band bias — fwd + grads."""
    from apex_tpu.ops.flash_attention import NEG_INF

    B, T, H, D = 1, 512, 2, 32
    q, k, v = (_rand((B, T, H, D), s) for s in range(3))
    band = jnp.where(
        (jnp.arange(T)[:, None] - jnp.arange(T)[None, :]) < window,
        0.0, NEG_INF)

    def f(q, k, v):
        return flash_attention(q, k, v, causal=True, window=window,
                               block_q=128, block_k=128, interpret=True)

    def ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True,
                                     bias=band[None, None])

    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda *a: jnp.sum(jnp.sin(f(*a))), argnums=(0, 1, 2))(
        q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))), argnums=(0, 1, 2))(
        q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_window_with_bias_and_mqa():
    """window + learnable [B,T,S] bias + MQA compose; dbias is zero
    outside the band (the db2 pass keeps the full grid so out-of-band
    blocks are written, not left undefined)."""
    from apex_tpu.ops.flash_attention import NEG_INF

    # T=512, W=100 (span 2 < nk 4): the BOUNDED grid runs, covering the
    # clamped bias index maps under virtual-negative ki.
    B, T, H, D, W = 1, 512, 2, 32, 100
    q = _rand((B, T, H, D), 0)
    k1 = _rand((B, T, 1, D), 1)
    v1 = _rand((B, T, 1, D), 2)
    bias = _rand((B, T, T), 3) * 0.3
    band = jnp.where(
        (jnp.arange(T)[:, None] - jnp.arange(T)[None, :]) < W, 0.0, NEG_INF)

    def f(q, k, v, bi):
        return flash_attention(q, k, v, causal=True, window=W, bias=bi,
                               block_q=128, block_k=128, interpret=True)

    def ref(q, k, v, bi):
        return dot_product_attention(
            q, jnp.repeat(k, H, 2), jnp.repeat(v, H, 2), causal=True,
            bias=bi[:, None] + band[None, None])

    g1 = jax.grad(lambda *a: jnp.sum(f(*a) ** 2), argnums=(0, 1, 2, 3))(
        q, k1, v1, bias)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2), argnums=(0, 1, 2, 3))(
        q, k1, v1, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)
    assert np.isfinite(np.asarray(g1[3])).all()
    # out-of-band bias grad is exactly zero
    oob = np.asarray(g1[3])[0][np.asarray(band) < -1e29]
    assert np.all(oob == 0.0)


def test_flash_window_requires_causal():
    q, k, v = (_rand((1, 128, 2, 32), s) for s in range(3))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=64, interpret=True)


def test_flash_dispatch_predicate():
    """r5 shape dispatch: defaults below the crossover route to jnp; an
    explicit block size (even equal to the default values) forces the
    kernel; at/above the crossover defaults keep the kernel."""
    from apex_tpu.ops.flash_attention import (_KERNEL_MIN_KV,
                                              _dispatch_to_jnp)
    small = _KERNEL_MIN_KV // 2
    assert _dispatch_to_jnp(small, small, True)
    assert not _dispatch_to_jnp(small, small, False)   # explicit blocks
    assert not _dispatch_to_jnp(_KERNEL_MIN_KV, _KERNEL_MIN_KV, True)
    # mixed: a long KV with short Q (decode-ish chunk) keeps the kernel
    assert not _dispatch_to_jnp(small, _KERNEL_MIN_KV, True)


def test_flash_dispatch_routes_to_jnp_numerics():
    """The dispatched (jnp) path computes the same function: defaults at a
    sub-crossover shape vs explicit-block kernel in interpret mode."""
    q, k, v = (_rand((2, 128, 2, 32), s) for s in range(3))
    # defaults: sub-crossover -> jnp path (off-TPU it is the fallback
    # anyway; the assert is on VALUES, which must agree either way)
    out_default = flash_attention(q, k, v, causal=True)
    out_kernel = flash_attention(q, k, v, causal=True,
                                 block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out_default),
                               np.asarray(out_kernel), atol=2e-2, rtol=2e-2)


# -- decode-shaped causal inputs (ISSUE 11 satellite) -------------------------

def _suffix_causal_ref(q, k, v, key_padding_bias=None):
    """Reference for decode-shaped causal attention: run the FULL causal
    oracle over the whole sequence (queries = the last tq positions) and
    slice the suffix rows — token-for-token what a KV-cache decode must
    reproduce."""
    tq, tk = q.shape[1], k.shape[1]
    # embed the queries at their true (suffix) positions: pad with the
    # keys' own projections so positions 0..tk-tq-1 exist, then slice.
    bias = None
    if key_padding_bias is not None:
        bias = key_padding_bias[:, None, None, :]
    qi = (tk - tq) + jnp.arange(tq)[:, None]
    ki = jnp.arange(tk)[None, :]
    causal = jnp.where(qi >= ki, 0.0, -1e30)[None, None]
    bias = causal if bias is None else bias + causal
    return dot_product_attention(q, k, v, causal=False, bias=bias)


@pytest.mark.parametrize("tq", [1, 4, 7])
def test_decode_shaped_causal_matches_reference(tq):
    """causal with q_len < kv_len must suffix-align the queries (the
    KV-cache decode convention) — before the fix a q_len=1 causal call
    silently attended only key 0."""
    B, TK, H, D = 2, 96, 2, 16
    q = _rand((B, tq, H, D), 0)
    k = _rand((B, TK, H, D), 1)
    v = _rand((B, TK, H, D), 2)
    out = flash_attention(q, k, v, causal=True)
    ref = _suffix_causal_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_shaped_causal_with_live_mask():
    """One fresh token over a cache of TK slots with only the first L
    live (key_padding_bias masks the dead tail) — the serving engine's
    decode call shape."""
    B, TK, H, D = 3, 64, 2, 16
    live_len = jnp.array([5, 17, 64])
    q = _rand((B, 1, H, D), 3)
    k = _rand((B, TK, H, D), 4)
    v = _rand((B, TK, H, D), 5)
    kb = jnp.where(jnp.arange(TK)[None, :] < live_len[:, None], 0.0, -1e9)
    out = flash_attention(q, k, v, causal=True, key_padding_bias=kb)
    ref = _suffix_causal_ref(q, k, v, key_padding_bias=kb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_shaped_causal_kernel_path_matches():
    """A sublane-aligned short query block keeps the kernel path
    (interpret mode) — suffix alignment must hold there too, not only on
    the jnp fallback."""
    B, TQ, TK, H, D = 1, 8, 128, 2, 16
    q = _rand((B, TQ, H, D), 6)
    k = _rand((B, TK, H, D), 7)
    v = _rand((B, TK, H, D), 8)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=128,
                          interpret=True)
    ref = _suffix_causal_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_more_queries_than_keys_raises():
    q = _rand((1, 8, 2, 16), 0)
    k = _rand((1, 4, 2, 16), 1)
    with pytest.raises(ValueError, match="q_len"):
        flash_attention(q, k, q * 0, causal=True)
