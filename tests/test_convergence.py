"""Convergence gate at suite scale (VERDICT r2 next #2): O2 (bf16 +
dynamic scaling) must TRACK O0 (fp32) over hundreds of optimization
steps, not just the 6-step trajectory parity of test_l1_cross_product.
The full-depth on-chip artifact is produced by tools/convergence.py
(CONVERGENCE_r03.json); this test runs the same gate() on a small MLP so
the property is enforced on every CI run.  Reference anchor:
/root/reference/tests/L1/common/run_test.sh + compare.py (epoch-scale
loss-curve comparison across opt levels).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
from convergence import gate  # noqa: E402

from apex_tpu import training  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402

STEPS = 250


def _mlp_curve(opt_level, loss_scale, steps=STEPS, seed=0):
    """Small-MLP classification on a fixed, memorizable dataset."""
    rng = np.random.RandomState(seed)
    n, d, h, c = 256, 32, 64, 10
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    y = jnp.asarray(rng.randint(0, c, n))
    params = {
        "w1": jnp.asarray(rng.randn(d, h) * (1 / np.sqrt(d)), jnp.float32),
        "b1": jnp.zeros((h,), jnp.float32),
        "w2": jnp.asarray(rng.randn(h, c) * (1 / np.sqrt(h)), jnp.float32),
        "b2": jnp.zeros((c,), jnp.float32),
    }

    def loss_fn(p, batch):
        xb, yb = batch
        z = jnp.tanh(xb @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(z.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

    tx = training.sgd(lr=0.5, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       loss_scale=loss_scale)
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    losses = []
    for _ in range(steps):
        state, m = step(state, (x, y))
        losses.append(float(m["loss"]))
    return losses


def test_o2_dynamic_tracks_o0_at_depth():
    losses_o0 = _mlp_curve("O0", None)
    losses_o2 = _mlp_curve("O2", "dynamic")
    verdict = gate(losses_o0, losses_o2)
    assert verdict["o0_learned"], verdict
    assert verdict["o2_learned"], verdict
    assert verdict["o2_tracks_o0"], verdict


def test_convergence_artifact_if_present():
    """When the on-chip artifact exists in the repo, its recorded verdict
    must be green and self-consistent with its own curves."""
    path = Path(__file__).resolve().parent.parent / "CONVERGENCE_r03.json"
    if not path.exists():
        pytest.skip("no on-chip convergence artifact in this checkout")
    import json

    art = json.loads(path.read_text())
    assert art["verdict"]["ok"], art["verdict"]
    recomputed = gate(art["losses_o0"], art["losses_o2"])
    assert recomputed["ok"], recomputed
    assert len(art["losses_o0"]) == art["config"]["steps"]
