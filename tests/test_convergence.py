"""Convergence gate at suite scale (VERDICT r2 next #2): O2 (bf16 +
dynamic scaling) must TRACK O0 (fp32) over hundreds of optimization
steps, not just the 6-step trajectory parity of test_l1_cross_product.
The full-depth on-chip artifact is produced by tools/convergence.py
(CONVERGENCE_r03.json); this test runs the same gate() on a small MLP so
the property is enforced on every CI run.  Reference anchor:
/root/reference/tests/L1/common/run_test.sh + compare.py (epoch-scale
loss-curve comparison across opt levels).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
from convergence import gate  # noqa: E402

from apex_tpu import training  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402

STEPS = 250


def _mlp_curve(opt_level, loss_scale, steps=STEPS, seed=0):
    """Small-MLP classification on a fixed, memorizable dataset."""
    rng = np.random.RandomState(seed)
    n, d, h, c = 256, 32, 64, 10
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    y = jnp.asarray(rng.randint(0, c, n))
    params = {
        "w1": jnp.asarray(rng.randn(d, h) * (1 / np.sqrt(d)), jnp.float32),
        "b1": jnp.zeros((h,), jnp.float32),
        "w2": jnp.asarray(rng.randn(h, c) * (1 / np.sqrt(h)), jnp.float32),
        "b2": jnp.zeros((c,), jnp.float32),
    }

    def loss_fn(p, batch):
        xb, yb = batch
        z = jnp.tanh(xb @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(z.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

    tx = training.sgd(lr=0.5, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       loss_scale=loss_scale)
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    losses = []
    for _ in range(steps):
        state, m = step(state, (x, y))
        losses.append(float(m["loss"]))
    return losses


def test_o2_dynamic_tracks_o0_at_depth():
    losses_o0 = _mlp_curve("O0", None)
    losses_o2 = _mlp_curve("O2", "dynamic")
    verdict = gate(losses_o0, losses_o2)
    assert verdict["o0_learned"], verdict
    assert verdict["o2_learned"], verdict
    assert verdict["o2_tracks_o0"], verdict


@pytest.mark.slow
def test_deep_dp_trajectory_tracks_single_process():
    """120+ steps of 8-way DP (shard_map + SyncBN + DDP grad averaging)
    vs the single-process whole-batch run on ResNet-18 — the depth gate
    VERDICT r3 next #7 asked for (reference anchor:
    tests/L1/cross_product_distributed/run.sh trains real epochs).
    Two tiers: O0/fp32 with the tight per-step head gate (isolates
    reduction order), O2/bf16 statistical (bf16 quantization flips make
    per-step agreement meaningless past a few steps — see gate_dp)."""
    from convergence import gate_dp, run_curve

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    # use_sync_bn=True: the oracle must share the DP run's statistics
    # arithmetic (SyncBN with axis=None) — see _run_curve_inner's note.
    kw = dict(batch=32, image_size=32, num_classes=10, lr=0.02,
              log_every=0, use_sync_bn=True)
    single0, _ = run_curve("O0", 120, **kw)
    dp0, _ = run_curve("O0", 120, dp=8, **kw)
    v0 = gate_dp(single0, dp0, head_gate=True)
    assert v0["ok"], v0
    single2, _ = run_curve("O2", 120, loss_scale="dynamic", **kw)
    dp2, _ = run_curve("O2", 120, dp=8, loss_scale="dynamic", **kw)
    v2 = gate_dp(single2, dp2, head_gate=False)
    assert v2["ok"], v2


def test_convergence_artifact_if_present():
    """When on-chip artifacts exist in the repo, every recorded verdict
    must be green and self-consistent with its own curves (newest and
    older rounds alike)."""
    arts = sorted(
        Path(__file__).resolve().parent.parent.glob("CONVERGENCE*.json"))
    if not arts:
        pytest.skip("no on-chip convergence artifact in this checkout")
    import json

    from convergence import gate_dp

    for path in arts:
        art = json.loads(path.read_text())
        if art.get("kind") == "quant":
            # O4-vs-O2 artifact (tools/convergence_quant.py): recompute
            # the gate from the shipped curves under the artifact's own
            # tolerance (a stale ok flag must not pass).
            assert art["verdict"]["ok"], (path.name, art["verdict"])
            recomputed = gate(art["losses_o2"], art["losses_o4"],
                              track_tol=art["verdict"]["track_tol"])
            assert recomputed["ok"], (path.name, recomputed)
            assert len(art["losses_o4"]) == art["config"]["steps"]
            continue
        if "verdicts" in art:
            # sharded-topology artifact (tools/convergence_sharded.py):
            # different schema — every topology verdict must be green AND
            # recompute from the shipped curves (a stale ok flag over
            # regenerated curves must not pass).
            assert art["ok"], (path.name, art["verdicts"])
            for topo, v in art["verdicts"].items():
                assert v["ok"], (path.name, topo, v)
                curves = art[f"losses_{topo}"]
                re0 = gate_dp(curves["O0_single"], curves["O0_sharded"],
                              head_gate=True)
                re2 = gate_dp(curves["O2_single"], curves["O2_sharded"],
                              head_gate=False)
                assert re0["ok"] and re2["ok"], (path.name, topo, re0, re2)
            continue
        assert art["verdict"]["ok"], (path.name, art["verdict"])
        recomputed = gate(art["losses_o0"], art["losses_o2"])
        assert recomputed["ok"], (path.name, recomputed)
        assert len(art["losses_o0"]) == art["config"]["steps"]
        if "dp_verdict" in art:
            re0 = gate_dp(art["losses_o0_single_syncbn"],
                          art["losses_o0_dp_syncbn"], head_gate=True)
            re2 = gate_dp(art["losses_o2_single_syncbn"],
                          art["losses_o2_dp_syncbn"], head_gate=False)
            assert re0["ok"] and re2["ok"], (path.name, re0, re2)
