"""ZeRO-1 optimizer-state sharding: identical trajectories to plain DP,
state memory divided by the axis size."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import training
from apex_tpu.parallel.zero import zero1, zero1_partition_spec
from apex_tpu.training import TrainState, make_train_step

N = 4


@pytest.fixture
def dp_mesh():
    return Mesh(np.array(jax.devices("cpu")[:N]), ("data",))


def _setup():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(5, 7) * 0.3, jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}   # 38 elems: pad to 40
    x = jnp.asarray(rng.randn(8 * N, 5), jnp.float32)
    y = jnp.asarray(rng.randn(8 * N, 7) * 0.1, jnp.float32)
    return params, x, y


def _loss_fn(p, batch):
    xb, yb = batch
    pred = xb @ p["w"] + jnp.pad(p["b"], (0, 4))
    return jnp.mean((pred - yb) ** 2)


def _run(dp_mesh, tx, opt_spec, axis_name, steps=5, loss_scale=None,
         reduce_grads=True, batch=None):
    params, x, y = _setup()
    if batch is not None:
        x, y = batch
    init_fn, step_fn = make_train_step(_loss_fn, tx, opt_level="O2",
                                       loss_scale=loss_scale,
                                       axis_name=axis_name,
                                       reduce_grads=reduce_grads)
    state = init_fn(params)
    state_spec = TrainState(params=P(), opt_state=opt_spec,
                            scaler=P(), model_state=P())

    def wrapped(s, b):
        ns, m = step_fn(s, b)
        m = jax.tree_util.tree_map(
            lambda v: training._pmean_varying(v, ("data",)), m)
        return ns, m

    step = jax.jit(shard_map(
        wrapped, mesh=dp_mesh,
        in_specs=(state_spec, (P("data"), P("data"))),
        out_specs=(state_spec, P())))
    losses = []
    for _ in range(steps):
        state, metrics = step(state, (x, y))
        losses.append(float(jnp.ravel(metrics["loss"])[0]))
    return np.asarray(losses), state


def test_zero1_matches_plain_dp(dp_mesh):
    plain_tx = training.adam(1e-2)
    plain_losses, _ = _run(dp_mesh, plain_tx, P(), axis_name=("data",))

    z_tx = zero1(training.adam(1e-2), "data", num_shards=N)
    z_state0 = z_tx.init(_setup()[0])
    z_spec = zero1_partition_spec(z_state0, "data")
    zero_losses, _ = _run(dp_mesh, z_tx, z_spec, axis_name=("data",),
                          reduce_grads=False)

    np.testing.assert_allclose(zero_losses, plain_losses,
                               rtol=1e-5, atol=1e-7)
    assert zero_losses[-1] < zero_losses[0]


def test_zero1_with_dynamic_scaling(dp_mesh):
    z_tx = zero1(training.adam(1e-2), "data", num_shards=N)
    z_spec = zero1_partition_spec(z_tx.init(_setup()[0]), "data")
    losses, _ = _run(dp_mesh, z_tx, z_spec, axis_name=("data",),
                     reduce_grads=False, loss_scale="dynamic")
    assert np.all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_zero1_overflow_on_one_rank_skips_everywhere(dp_mesh):
    """One rank's local inf grads must skip the step on EVERY rank: the
    reduce-scattered chunks of non-overflowing ranks contain the inf
    contribution, so a locally-decided mask would poison their moments
    (the reason zero1 requires axis_name + reduce_grads=False)."""
    params, x, y = _setup()
    x = x.at[0, 0].set(np.inf)          # rank 0's shard only
    z_tx = zero1(training.adam(1e-2), "data", num_shards=N)
    z_spec = zero1_partition_spec(z_tx.init(params), "data")
    _, state = _run(dp_mesh, z_tx, z_spec, axis_name=("data",),
                    reduce_grads=False, loss_scale="dynamic", steps=1,
                    batch=(x, y))
    # params untouched (global skip), moments finite everywhere, scale halved
    np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                  np.asarray(params["w"]))
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
    assert float(state.scaler.loss_scale) == 2.**15


def test_zero1_state_is_actually_sharded(dp_mesh):
    """Each rank's flat moment chunks are 1/N of the padded total."""
    params, _, _ = _setup()
    z_tx = zero1(training.adam(1e-2), "data", num_shards=N)
    state = z_tx.init(params)
    flat_len = state.inner.exp_avg.size
    assert flat_len % N == 0
    assert flat_len >= 38                          # padded 38 -> 40

    def probe(st):
        return jnp.asarray(st.inner.exp_avg.shape[0])

    spec = zero1_partition_spec(state, "data")
    per_rank = jax.jit(shard_map(
        probe, mesh=dp_mesh, in_specs=(spec,), out_specs=P(),
        check_vma=False))(state)
    assert int(per_rank) == flat_len // N


def test_zero1_rejects_per_tensor_norm_optimizers():
    # lamb/novograd do NOT declare elementwise=True (per-tensor trust
    # ratios are wrong on flat chunks).
    with pytest.raises(ValueError, match="elementwise"):
        zero1(training.lamb(1e-3), "data", num_shards=4)
    with pytest.raises(ValueError, match="elementwise"):
        zero1(training.novograd(1e-3), "data", num_shards=4)


def test_zero1_rejects_unknown_optimizers_by_default():
    """Capability is declared, not name-sniffed (ADVICE r2): a third-party
    optimizer without elementwise=True is rejected even if its name looks
    innocent; opting in works."""
    from apex_tpu.training import FunctionalOptimizer

    sneaky = FunctionalOptimizer(init=lambda p: None,
                                 update=lambda g, s, p, **kw: (p, s))
    with pytest.raises(ValueError, match="elementwise"):
        zero1(sneaky, "data", num_shards=4)

    ok = sneaky._replace(elementwise=True)
    zero1(ok, "data", num_shards=4)      # accepted


def test_zero1_rejects_mixed_dtypes():
    z_tx = zero1(training.adam(1e-2), "data", num_shards=4)
    with pytest.raises(ValueError, match="uniform parameter dtype"):
        z_tx.init({"a": jnp.zeros(3, jnp.float32),
                   "b": jnp.zeros(3, jnp.bfloat16)})


@pytest.mark.skipif(
    __import__("apex_tpu.parallel.zero", fromlist=["_all_gather_invariant"])
    ._all_gather_invariant is None,
    reason="this jax has no all_gather_invariant; zero1 uses the "
           "masked-psum fallback")
def test_zero1_uses_invariant_gather_under_default_vma(dp_mesh):
    """Under shard_map's DEFAULT vma tracking the param gather must be the
    cheap Varying->Invariant all-gather, not the masked-psum workaround
    (a full all-reduce of a zeros-placed buffer) — VERDICT r2 weak #8."""
    z_tx = zero1(training.adam(1e-2), "data", num_shards=N)
    init_fn, step_fn = make_train_step(_loss_fn, z_tx, opt_level="O2",
                                       axis_name=("data",),
                                       reduce_grads=False)
    params, x, y = _setup()
    state = init_fn(params)
    state_spec = TrainState(params=P(),
                            opt_state=zero1_partition_spec(
                                state.opt_state, "data"),
                            scaler=P(), model_state=P())
    def wrapped(s, b):
        ns, m = step_fn(s, b)
        m = jax.tree_util.tree_map(
            lambda v: training._pmean_varying(v, ("data",)), m)
        return ns, m

    stepped = shard_map(wrapped, mesh=dp_mesh,
                        in_specs=(state_spec, (P("data"), P("data"))),
                        out_specs=(state_spec, P()))         # default vma
    jaxpr = str(jax.make_jaxpr(stepped)(state, (x, y)))
    assert "all_gather_invariant" in jaxpr
