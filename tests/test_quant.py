"""apex_tpu.quant — the int8 low-precision engine (ISSUE 13).

Acceptance contracts under test:

* kernel parity matrix: the REAL Pallas kernel (interpret mode) against
  the jnp reference, forward AND backward, per-tensor + per-channel
  scales, incl. the zero-amax-channel corner;
* the model hook: O4 with an empty/missing calibration is BITWISE O2
  (never silent degradation), a frozen calibration quantizes only the
  calibrated sites;
* calibration lifecycle: observe → freeze → checkpoint-extra round-trip
  (the serving restore path);
* O4-vs-O2 convergence tolerance on the small LM (the CI-scale twin of
  CONVERGENCE_QUANT.json);
* int8 KV cache: scatter/gather round-trip within quantization
  tolerance, decode parity vs the full-precision pool, hot-swap bitwise
  stability, and the >= 1.5x equal-HBM page-capacity claim;
* zero steady-state retraces of the quantized step under
  ``StepPipeline.warmup`` (trace-count pinned).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from apex_tpu import quant, runtime, serving, training  # noqa: E402
from apex_tpu.models.gpt import gpt_tiny  # noqa: E402
from apex_tpu.prof import assert_trace_count  # noqa: E402
from apex_tpu.quant import kernels as QK  # noqa: E402
from apex_tpu.serving import kv_cache as KV  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402


# -- kernel parity matrix -----------------------------------------------------

def _operands(m, k, n, dtype, seed=0, zero_channel=None):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(m, k), dtype)
    w = np.asarray(rs.randn(k, n) / np.sqrt(k), np.float32)
    if zero_channel is not None:
        w[:, zero_channel] = 0.0
    w = jnp.asarray(w, dtype)
    xs = float(np.abs(np.asarray(x, np.float32)).max()) / 127.0
    return x, w, xs


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("m,k,n", [(32, 64, 48), (17, 96, 130), (8, 8, 8)])
def test_kernel_fwd_interpret_matches_reference(dtype, m, k, n):
    """The REAL kernel (interpret=True) against the jnp reference —
    quantize, int8 dot, dequant epilogue are op-identical, so the
    parity is exact, including ragged M/N blocks."""
    x, w, xs = _operands(m, k, n, dtype)
    ref = quant.quantized_matmul_ref(x, w, x_scale=xs)
    out = quant.quantized_matmul(x, w, x_scale=xs, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref, np.float32),
                                  np.asarray(out, np.float32))
    jnp_out = quant.quantized_matmul(x, w, x_scale=xs, impl="jnp")
    np.testing.assert_array_equal(np.asarray(ref, np.float32),
                                  np.asarray(jnp_out, np.float32))


def test_kernel_accuracy_vs_full_precision():
    """int8 with per-channel weight scales lands ~1% RMS of the full
    matmul — the LLM.int8() ballpark; a broken scale convention would
    be off by orders of magnitude."""
    x, w, xs = _operands(64, 128, 96, jnp.float32, seed=3)
    full = np.asarray(x) @ np.asarray(w)
    q = np.asarray(quant.quantized_matmul(x, w, x_scale=xs, impl="jnp"))
    rel = np.sqrt(((q - full) ** 2).mean()) / np.sqrt((full ** 2).mean())
    assert rel < 0.03, rel


def test_kernel_bwd_is_bf16_straight_through():
    """The custom VJP: dx/dw computed from the SAVED full-precision
    operands in their own dtype (bf16 backward), identical between the
    interpret kernel and the reference path, and equal to the plain
    matmul's gradients (straight-through)."""
    x, w, xs = _operands(16, 32, 24, jnp.bfloat16, seed=1)

    def loss(fn):
        return lambda x, w: jnp.sum(
            fn(x, w).astype(jnp.float32) ** 2) / 100.0

    def qloss(x, w, **kw):
        return jnp.sum(quant.quantized_matmul(
            x, w, x_scale=xs, **kw).astype(jnp.float32) ** 2) / 100.0

    gx_i, gw_i = jax.grad(lambda x, w: qloss(x, w, interpret=True),
                          argnums=(0, 1))(x, w)
    gx_j, gw_j = jax.grad(lambda x, w: qloss(x, w, impl="jnp"),
                          argnums=(0, 1))(x, w)
    assert gx_i.dtype == jnp.bfloat16 and gw_i.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(gx_i, np.float32),
                                  np.asarray(gx_j, np.float32))
    np.testing.assert_array_equal(np.asarray(gw_i, np.float32),
                                  np.asarray(gw_j, np.float32))
    # straight-through: cotangents flow as if the matmul were exact,
    # seeded by the QUANTIZED forward's output (g = 2*out/100)
    out = quant.quantized_matmul(x, w, x_scale=xs, impl="jnp")
    g = (2.0 * out.astype(jnp.float32) / 100.0).astype(jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(gx_j, np.float32),
        np.asarray(jnp.dot(g, w.T).astype(jnp.bfloat16), np.float32))


def test_impl_jnp_wins_over_interpret_and_bogus_impl_rejected(monkeypatch):
    """impl="jnp" is the explicit "reference on this exact call" A/B
    probe — interpret=True must not override it (review), and a bogus
    impl must raise even when interpret is set."""
    from apex_tpu.quant import kernels as K

    x, w, xs = _operands(8, 32, 16, jnp.float32)

    def _boom(*a, **k):
        raise AssertionError("pallas path dispatched under impl='jnp'")

    monkeypatch.setattr(K, "_pallas_qmm", _boom)
    out = quant.quantized_matmul(x, w, x_scale=xs, impl="jnp",
                                 interpret=True)
    ref = quant.quantized_matmul_ref(x, w, x_scale=xs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    with pytest.raises(ValueError, match="impl"):
        quant.quantized_matmul(x, w, x_scale=xs, impl="bogus",
                               interpret=True)


def test_zero_amax_channel_corner():
    """An all-zero weight column must quantize to exact zeros (scale
    guard 1.0), produce exact-zero outputs, and not poison neighbors."""
    x, w, xs = _operands(16, 32, 24, jnp.float32, zero_channel=5)
    for kw in ({"impl": "jnp"}, {"interpret": True}):
        out = np.asarray(quant.quantized_matmul(x, w, x_scale=xs, **kw))
        assert np.all(out[:, 5] == 0.0)
        assert np.all(np.isfinite(out))
    # and a zero-amax ACTIVATION tensor round-trips as zeros
    z = jnp.zeros((4, 32), jnp.float32)
    out = quant.quantized_matmul(z, w, x_scale=quant.amax_to_scale(0.0),
                                 impl="jnp")
    assert np.all(np.asarray(out) == 0.0)


def test_quantize_dequantize_roundtrip_and_saturation():
    x = jnp.asarray([[0.5, -1.0, 2.0, 0.0]], jnp.float32)
    scale = quant.amax_to_scale(jnp.max(jnp.abs(x)))
    q = quant.quantize(x, scale)
    assert q.dtype == jnp.int8
    back = quant.dequantize(q, scale)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(scale) / 2 + 1e-7)
    # saturation_count: elements past the calibrated range
    assert int(quant.saturation_count(x, scale)) == 0
    # |x*2| = [1, 2, 4, 0] against limit 2: only the 4 clips (strict >;
    # exactly-at-limit quantizes to ±127 without clipping)
    assert int(quant.saturation_count(x * 2.0, scale)) == 1


# -- model hook ---------------------------------------------------------------

def _tiny_lm(quant_cfg=None):
    return gpt_tiny(dtype=jnp.bfloat16, attention_impl="blockwise",
                    quant=quant_cfg)


def _lm_batch(seed=0, batch=2, seq=16):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randint(1, 1024, (batch, seq)))


def test_o4_without_calibration_is_bitwise_o2():
    """The acceptance fallback: a quant-hooked model with NO frozen
    scales computes bit-for-bit what the plain model computes — O4
    degrades to O2, never to silently different numerics."""
    ids = _lm_batch()
    plain = _tiny_lm()
    params = plain.init(jax.random.PRNGKey(0), ids)["params"]
    hooked = _tiny_lm(quant.QuantConfig(mode="quant", scales={}))
    np.testing.assert_array_equal(
        np.asarray(plain.apply({"params": params}, ids)),
        np.asarray(hooked.apply({"params": params}, ids)))
    # param trees are interchangeable (same names, shapes, init draws)
    p2 = hooked.init(jax.random.PRNGKey(0), ids)["params"]
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(p2))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, p2)


def _calibrate_tiny(params, ids, n=2):
    obs = _tiny_lm(quant.QuantConfig.observe())
    cal = quant.Calibrator()
    for i in range(n):
        _, st = obs.apply({"params": params}, _lm_batch(seed=i),
                          mutable=["quant_stats"])
        cal.harvest(jax.device_get(st["quant_stats"]))
    return cal


def test_observe_phase_collects_every_projection_site():
    ids = _lm_batch()
    params = _tiny_lm().init(jax.random.PRNGKey(0), ids)["params"]
    cal = _calibrate_tiny(params, ids)
    # gpt_tiny: 2 blocks x (q, k, v, out, mlp_up, mlp_down) = 12 sites
    assert len(cal.sites) == 12, cal.sites
    assert "block_0/mlp_up" in cal.sites
    assert "block_1/attention/query" in cal.sites
    calib = cal.freeze()
    assert all(s > 0 for s in calib.scales.values())
    # percentile mode clips the history's outlier tail
    p = cal.freeze(mode=50.0)
    assert all(p.amax[k] <= calib.amax[k] for k in calib.amax)


def test_frozen_calibration_quantizes_and_stays_finite():
    ids = _lm_batch()
    params = _tiny_lm().init(jax.random.PRNGKey(0), ids)["params"]
    calib = _calibrate_tiny(params, ids).freeze()
    qm = _tiny_lm(quant.QuantConfig.frozen(calib))
    l_q = np.asarray(qm.apply({"params": params}, ids), np.float32)
    l_p = np.asarray(_tiny_lm().apply({"params": params}, ids),
                     np.float32)
    assert np.all(np.isfinite(l_q))
    assert not np.array_equal(l_q, l_p)      # the int8 path really ran
    # interpret mode (the REAL kernel) agrees with the jnp quant path
    qi = _tiny_lm(quant.QuantConfig.frozen(calib, interpret=True))
    np.testing.assert_array_equal(
        l_q, np.asarray(qi.apply({"params": params}, ids), np.float32))


# -- calibration round-trip through checkpoint extras -------------------------

def test_calibration_checkpoint_extra_roundtrip(tmp_path):
    from apex_tpu.checkpoint import (CheckpointManager,
                                     latest_checkpoint,
                                     load_checkpoint_dir)

    ids = _lm_batch()
    params = _tiny_lm().init(jax.random.PRNGKey(0), ids)["params"]
    calib = _calibrate_tiny(params, ids).freeze()
    state = {"w": jnp.ones((3,), jnp.float32)}
    with CheckpointManager(str(tmp_path), async_write=False) as mgr:
        mgr.save(7, state, quant_calibration=calib.state_dict())
    restored = load_checkpoint_dir(latest_checkpoint(str(tmp_path)),
                                   like=state)
    back = quant.Calibration.from_state_dict(
        restored.extra["quant_calibration"])
    assert back.scales == calib.scales
    assert back.amax == calib.amax
    assert back.meta["mode"] == "max"
    # and the restored scales drive the model identically
    a = _tiny_lm(quant.QuantConfig.frozen(calib)).apply(
        {"params": params}, ids)
    b = _tiny_lm(quant.QuantConfig.frozen(back)).apply(
        {"params": params}, ids)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_calibration_rejects_unknown_version_and_empty_freeze():
    with pytest.raises(ValueError, match="version"):
        quant.Calibration.from_state_dict({"version": 99})
    with pytest.raises(ValueError, match="observation"):
        quant.Calibrator().freeze()
    with pytest.raises(ValueError, match="percentile"):
        c = quant.Calibrator()
        c.observe("a", 1.0)
        c.freeze(mode=0.0)


# -- O4 training: convergence + trace pins ------------------------------------

def _o4_setup(calibrated=True):
    from convergence_quant import (build_model, calibrate,
                                   make_lm_dataset)

    model_kw = dict(vocab=64, hidden=64, layers=2, heads=4, seq=32)
    batches = make_lm_dataset(16, 4, 32, 64)
    plain = build_model(None, **model_kw)
    params = plain.init(jax.random.PRNGKey(0),
                        jnp.asarray(batches[0][:, :-1]))["params"]
    calib = calibrate(params, batches, **model_kw) if calibrated else None
    model = build_model(
        quant.QuantConfig.frozen(calib) if calibrated else None,
        **model_kw)

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b[:, :-1])
        logp = jax.nn.log_softmax(
            logits.reshape(-1, 64).astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(
            logp, b[:, 1:].reshape(-1)[:, None], axis=1))

    return params, batches, loss_fn


def test_o4_tracks_o2_on_the_small_lm():
    """The CI-scale CONVERGENCE_QUANT gate: 120 steps of the noisy-
    bigram LM, O4's curve tracks O2's (the on-chip artifact runs the
    same harness at full depth — tools/convergence_quant.py)."""
    from convergence import gate
    from convergence_quant import run_lm_curve

    kw = dict(batch=8, seq=32, vocab=64, hidden=64, layers=2, lr=3e-3)
    losses_o2, _ = run_lm_curve("O2", 120, **kw)
    losses_o4, _ = run_lm_curve("O4", 120, **kw)
    v = gate(losses_o2, losses_o4, tail=30, track_tol=0.15)
    assert v["ok"], v


def test_o4_step_zero_retraces_under_warmup():
    """The quantized step through StepPipeline: frozen scales are trace
    constants, so AOT warmup pins ONE program and the whole run pays
    zero further traces (acceptance: zero steady-state retraces)."""
    params, batches, loss_fn = _o4_setup()
    tx = training.adam(lr=1e-3)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O4",
                                       loss_scale="dynamic")
    state = init_fn(params)
    K = 2
    pipe = runtime.StepPipeline(step_fn, K)

    def window(i=0):
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *batches[i:i + K])

    pipe.warmup(state, window())
    with assert_trace_count(pipe.loop, 0):
        for i in range(3):
            state, metrics = pipe.step_window(state, window(), K)
    losses = np.ravel(jax.device_get(metrics["loss"]))
    assert np.all(np.isfinite(losses))


def test_o4_state_layout_matches_o2():
    """O4 is O2's storage semantics exactly: fp32 stored params (the
    masters), identical optimizer-state tree, loss scaling wired."""
    params, batches, loss_fn = _o4_setup()
    tx = training.adam(lr=1e-3)
    init_o4, _ = make_train_step(loss_fn, tx, opt_level="O4",
                                 loss_scale="dynamic")
    init_o2, _ = make_train_step(loss_fn, tx, opt_level="O2",
                                 loss_scale="dynamic")
    s4, s2 = init_o4(params), init_o2(params)
    for leaf in jax.tree_util.tree_leaves(s4.params):
        assert leaf.dtype == jnp.float32
    assert (jax.tree_util.tree_structure(s4)
            == jax.tree_util.tree_structure(s2))
    assert float(s4.scaler.loss_scale) == float(s2.scaler.loss_scale)


# -- int8 KV cache ------------------------------------------------------------

def test_int8_pool_scatter_gather_roundtrip_tolerance():
    """Pool round-trip error bounded by the per-row quantization grid
    (scale/2 per element), per (token, head) scales."""
    model = gpt_tiny(max_len=64, dtype=jnp.float32)
    pool_k, pool_v = KV.make_pool(model, n_pages=5, page_size=4,
                                  dtype=jnp.int8)
    assert isinstance(pool_k, KV.QuantPool)
    assert pool_k.dtype == jnp.float32          # the dense-view dtype
    rs = np.random.RandomState(0)
    L, _, page, n_kv, hd = pool_k.shape
    bucket = 2 * page
    dense = jnp.asarray(rs.randn(L, bucket, n_kv, hd), jnp.float32)
    pages = jnp.asarray([1, 3], jnp.int32)
    pool_k = KV.scatter_prefill(pool_k, pages, dense)
    tables = np.asarray([[1, 3]], np.int32)
    views = KV.gather_views(pool_k, pool_v, tables)
    got = np.stack([k[0] for k, _ in views])    # [L, bucket, n_kv, hd]
    amax = np.abs(np.asarray(dense)).max(axis=-1, keepdims=True)
    np.testing.assert_allclose(got, np.asarray(dense),
                               atol=float(amax.max()) / 254 + 1e-6)
    # single-token scatter writes one row at the right offset
    tok = jnp.asarray(rs.randn(L, 1, n_kv, hd), jnp.float32)
    pool_k = KV.scatter_token(pool_k, jnp.asarray([3], jnp.int32),
                              jnp.asarray([2], jnp.int32), tok)
    views = KV.gather_views(pool_k, pool_v, tables)
    row = np.stack([k[0] for k, _ in views])[:, page + 2]
    np.testing.assert_allclose(
        row, np.asarray(tok)[:, 0],
        atol=float(np.abs(np.asarray(tok)).max()) / 254 + 1e-6)


def test_int8_kv_decode_parity_and_capacity():
    """End-to-end serving parity: the int8-KV engine decodes the same
    greedy tokens as the full-precision engine on the tiny LM (all
    deterministic — seeds fixed), pays zero AOT misses, and the
    equal-HBM page capacity is >= 1.5x bf16's."""
    model = gpt_tiny(max_len=128, dtype=jnp.float32)
    rs = np.random.RandomState(0)
    probe = jnp.asarray(rs.randint(1, 1024, (1, 8)))
    params = model.init(jax.random.PRNGKey(1), probe)["params"]
    prompts = [rs.randint(1, 1024, (n,)).astype(np.int32)
               for n in (5, 17, 30)]

    def run(dtype):
        eng = serving.ServingEngine(model, params, buckets=(32, 64),
                                    page_size=8, max_seqs=4,
                                    cache_dtype=dtype)
        eng.warmup()
        res = eng.generate(prompts, max_new_tokens=8)
        toks = [r.tokens for r in res]
        stats = dict(eng.stats)
        dt = eng.kv_cache_dtype
        eng.close()
        return toks, stats, dt

    t_ref, s_ref, dt_ref = run(None)
    t_q, s_q, dt_q = run(jnp.int8)
    assert dt_q == "int8" and dt_ref == "float32"
    for a, b in zip(t_ref, t_q):
        np.testing.assert_array_equal(a, b)
    assert s_q["aot_misses"] == 0
    assert s_q["kv_bytes_per_token"] < s_ref["kv_bytes_per_token"] / 2
    # equal-HBM capacity: int8 admits >= 1.5x the bf16 pages
    budget = 8 * 1024 * 1024
    bf16 = KV.pages_for_budget(model, 8, budget, jnp.bfloat16)
    i8 = KV.pages_for_budget(model, 8, budget, jnp.int8)
    assert i8 >= 1.5 * bf16, (i8, bf16)


def test_int8_kv_bitwise_stable_across_hotswap(tmp_path):
    """The acceptance gate: int8-KV serving through a mid-load weight
    hot-swap — post-swap output bitwise equals a fresh int8 engine on
    the new weights, and the run is deterministic end to end."""
    from apex_tpu.checkpoint import CheckpointManager

    model = gpt_tiny(max_len=64, dtype=jnp.float32)
    rs = np.random.RandomState(2)
    probe = jnp.asarray(rs.randint(1, 1024, (1, 8)))
    params = model.init(jax.random.PRNGKey(1), probe)["params"]
    params_v2 = jax.tree_util.tree_map(lambda x: x * 1.01, params)
    prompts = [rs.randint(1, 1024, (n,)).astype(np.int32)
               for n in (5, 12, 20)]
    eng = serving.ServingEngine(model, params, buckets=(32,),
                                page_size=8, max_seqs=2,
                                cache_dtype=jnp.int8,
                                watch_dir=str(tmp_path),
                                poll_every_s=3600)
    try:
        eng.warmup()
        comps = [eng.submit(p, 6) for p in prompts[:2]]
        for _ in range(3):
            eng.step()
        with CheckpointManager(str(tmp_path), procs=(0, 1),
                               async_write=False) as mgr:
            mgr.save(11, params_v2)
        assert eng.watcher.poll_once()
        comps += [eng.submit(prompts[2], 6)]
        eng.run_until_idle()
        assert all(c.result(timeout=0).ok for c in comps)
        assert eng.stats["hotswaps"] == 1
        post = eng.generate([prompts[0]], max_new_tokens=6)[0]
    finally:
        eng.close()
    ref = serving.ServingEngine(model, params_v2, buckets=(32,),
                                page_size=8, max_seqs=2,
                                cache_dtype=jnp.int8)
    try:
        ref.warmup()
        expect = ref.generate([prompts[0]], max_new_tokens=6)[0]
    finally:
        ref.close()
    np.testing.assert_array_equal(post.tokens, expect.tokens)


def test_serving_kv_stats_and_run_info_label(tmp_path):
    """kv_bytes_per_token rides the stats + a gauge, and the engine
    stamps kv_cache_dtype into the Prometheus run_info labels."""
    from apex_tpu import telemetry
    from apex_tpu.telemetry import export as T_export

    model = gpt_tiny(max_len=64, dtype=jnp.float32)
    probe = jnp.asarray(np.random.RandomState(0).randint(1, 1024, (1, 4)))
    params = model.init(jax.random.PRNGKey(1), probe)["params"]
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    try:
        eng = serving.ServingEngine(model, params, buckets=(32,),
                                    page_size=8, max_seqs=2,
                                    cache_dtype=jnp.int8)
        eng.warmup()
        eng.generate([np.asarray([5, 6, 7], np.int32)],
                     max_new_tokens=2)
        expo = T_export.render(rec)
        eng.close()
    finally:
        rec.close()
    assert 'kv_cache_dtype="int8"' in expo
    assert "serving_kv_bytes_per_token" in expo
    expected = KV.kv_bytes_per_token(model, jnp.int8)
    assert f"serving_kv_bytes_per_token {expected}" in expo


# -- saturation telemetry + watchdog ------------------------------------------

def test_saturation_note_feeds_quant_watchdog_rule(tmp_path):
    """Calibration.note_saturation -> quant event -> the
    quant_scale_saturation rule fires (and stays silent under the
    threshold)."""
    import json

    from apex_tpu import telemetry
    from apex_tpu.telemetry import watchdog as W

    calib = quant.Calibration({"block_0/mlp_up": 0.01},
                              {"block_0/mlp_up": 1.27})
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path, watchdog=True)
    try:
        calib.note_saturation("block_0/mlp_up", 2, window=32)   # benign
        calib.note_saturation("block_0/mlp_up", 9, window=32)   # burst
    finally:
        rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    alerts = [e for e in events if e.get("kind") == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["rule"] == "quant_scale_saturation"
    assert alerts[0]["severity"] == "warning"
    assert alerts[0]["value"] == 9
    quants = [e for e in events if e.get("kind") == "quant"]
    assert len(quants) == 2 and quants[0]["exceeded"] == 2
    assert calib.saturations == {"block_0/mlp_up": 11}
    # the rule is part of the default set
    assert "quant_scale_saturation" in W.RULE_NAMES


def test_saturation_count_drives_note(tmp_path):
    """The device-side count + the host note compose: quantize a tensor
    that outgrew its calibration and the counter reaches telemetry."""
    import json

    from apex_tpu import telemetry

    x = jnp.asarray(np.linspace(-2.0, 2.0, 64), jnp.float32)
    scale = quant.amax_to_scale(1.0)            # calibrated for |x|<=1
    n = int(quant.saturation_count(x, scale))
    assert n > 0
    calib = quant.Calibration({"s": float(scale)}, {"s": 1.0})
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    try:
        calib.note_saturation("s", n, window=1)
    finally:
        rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    ev = [e for e in events if e.get("kind") == "quant"]
    assert ev and ev[0]["exceeded"] == n


# -- amp plumbing -------------------------------------------------------------

def test_amp_o4_preset_and_frontend():
    from apex_tpu.amp.properties import AmpOptionError, opt_levels

    p = opt_levels["O4"]()
    assert p.master_weights and p.keep_batchnorm_fp32 and p.quantize
    assert jnp.dtype(p.cast_model_type) == jnp.dtype(jnp.bfloat16)
    assert not opt_levels["O2"]().quantize
    with pytest.raises(AmpOptionError, match="quantize"):
        p2 = opt_levels["O1"]()
        p2.quantize = True
    from apex_tpu import amp
    with pytest.raises(AmpOptionError, match="O4"):
        amp.initialize(models={"w": jnp.ones((2,))}, opt_level="O9")
    # the exclusivity holds through the OVERRIDE path too, not only on
    # quantize assignment (review: the preset sets quantize first, so
    # the patch_functions setter must also reject O4)
    with pytest.raises(AmpOptionError, match="O2/O3/O4"):
        amp.initialize(models={"w": jnp.ones((2,))}, opt_level="O4",
                       patch_functions=True)
    # and directly on the Properties surface, even with quantize unset
    p3 = opt_levels["O4"]()
    p3.quantize = False
    with pytest.raises(AmpOptionError, match="O2/O3/O4"):
        p3.patch_functions = True


def test_mesh_zero3_accepts_o4():
    from apex_tpu.parallel import mesh as M

    plan = M.MeshPlan(dp=1, fsdp=8, devices=jax.devices("cpu")[:8])

    def loss(p, b):
        return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

    ms = M.make_mesh_train_step(loss, training.adam(1e-2), plan,
                                zero=3, opt_level="O4")
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(5, 7) * 0.3, jnp.float32)}
    state = ms.init(params)
    step = ms.jit_step(state, donate=False)
    x = jnp.asarray(rs.randn(8, 5), jnp.float32)
    y = jnp.asarray(rs.randn(8, 7) * 0.1, jnp.float32)
    state, m = step(state, plan.device_put_batch((x, y)))
    assert np.isfinite(float(jnp.ravel(m["loss"])[0]))
