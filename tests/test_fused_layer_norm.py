"""FusedLayerNorm parity tests.

Mirrors reference ``tests/L0/run_fused_layer_norm``: compare against the
framework's own LayerNorm (flax) forward and backward, affine and
non-affine, multiple shapes and dtypes, plus torch CPU as an independent
oracle.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import flax.linen as nn

from apex_tpu.normalization import (FusedLayerNorm, fused_layer_norm,
                                    fused_layer_norm_affine)

SHAPES = [((4, 16), (16,)), ((2, 3, 32), (32,)), ((8, 6, 4), (6, 4)),
          ((5, 128), (128,))]


@pytest.mark.parametrize("shape,ns", SHAPES)
def test_forward_matches_torch(shape, ns):
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.randn(*ns).astype(np.float32)
    b = rng.randn(*ns).astype(np.float32)
    out = fused_layer_norm_affine(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), ns)
    expected = torch.nn.functional.layer_norm(
        torch.tensor(x), ns, torch.tensor(w), torch.tensor(b))
    np.testing.assert_allclose(np.asarray(out), expected.numpy(),
                               atol=1e-5, rtol=1e-5)


def test_forward_no_affine():
    rng = np.random.RandomState(1)
    x = rng.randn(6, 33).astype(np.float32)
    out = fused_layer_norm(jnp.asarray(x), 33)
    expected = torch.nn.functional.layer_norm(torch.tensor(x), (33,))
    np.testing.assert_allclose(np.asarray(out), expected.numpy(),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("shape,ns", SHAPES)
def test_backward_matches_torch(shape, ns):
    rng = np.random.RandomState(2)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.randn(*ns).astype(np.float32)
    b = rng.randn(*ns).astype(np.float32)

    def loss(x_, w_, b_):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x_, w_, b_, ns)))

    dx, dw, db = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))

    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    torch.sum(torch.sin(torch.nn.functional.layer_norm(tx, ns, tw, tb))).backward()
    np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), atol=1e-4,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dw), tw.grad.numpy(), atol=1e-4,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(db), tb.grad.numpy(), atol=1e-4,
                               rtol=1e-4)


def test_bf16_input_fp32_accumulation():
    rng = np.random.RandomState(3)
    x = rng.randn(4, 64).astype(np.float32)
    out_bf16 = fused_layer_norm(jnp.asarray(x, jnp.bfloat16), 64)
    out_f32 = fused_layer_norm(jnp.asarray(x), 64)
    assert out_bf16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_bf16, np.float32),
                               np.asarray(out_f32), atol=3e-2, rtol=3e-2)


def test_flax_module_matches_flax_layernorm():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(3, 48).astype(np.float32))
    m = FusedLayerNorm(normalized_shape=48)
    params = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(params, x)
    ref = nn.LayerNorm(epsilon=1e-5).apply(
        {"params": {"scale": params["params"]["scale"],
                    "bias": params["params"]["bias"]}}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_shape_mismatch_raises():
    with pytest.raises(ValueError):
        fused_layer_norm(jnp.ones((4, 8)), 16)


def test_jit_and_grad_composability():
    @jax.jit
    def f(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b, (32,)) ** 2)

    g = jax.jit(jax.grad(f))
    x = jnp.ones((4, 32)) + jnp.arange(32, dtype=jnp.float32)
    out = g(x, jnp.ones((32,)), jnp.zeros((32,)))
    assert out.shape == (4, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_impl_dispatch_crossover():
    """Auto dispatch: jnp below the measured in-context crossover, the
    pallas kernel at/above it; explicit impl= overrides; bad impl raises
    (r5, see _JNP_MAX_ELEMENTS in fused_layer_norm.py)."""
    import apex_tpu.normalization.fused_layer_norm  # noqa: F401
    fln = sys.modules["apex_tpu.normalization.fused_layer_norm"]
    orig = fln._use_pallas
    fln._use_pallas = lambda: True       # pretend we are on chip
    try:
        # BERT b16 x s128: 2048 x 768 = 1.57M elements -> jnp
        assert not fln._dispatch_pallas(2048, 768, None)
        # BERT b16 x s512: 8192 x 768 = 6.3M elements -> pallas
        assert fln._dispatch_pallas(8192, 768, None)
        assert fln._dispatch_pallas(2048, 768, "pallas")
        assert not fln._dispatch_pallas(8192, 768, "jnp")
        with pytest.raises(ValueError):
            fln._dispatch_pallas(8, 8, "cuda")
    finally:
        fln._use_pallas = orig
    # Off-TPU the hard gate wins even for impl="pallas".
    if jax.default_backend() != "tpu":
        assert not fln._dispatch_pallas(8192, 768, "pallas")


def test_pick_rows_vmem_budget():
    """Row blocks shrink with width so kernel VMEM stays bounded
    (r5 fix: [32768, 4096] bwd OOMed scoped VMEM at the fixed 256)."""
    import apex_tpu.normalization.fused_layer_norm  # noqa: F401
    fln = sys.modules["apex_tpu.normalization.fused_layer_norm"]
    BWD_BF16, BWD_F32 = 3 * 2 + 16, 3 * 4 + 16     # bytes/elem models
    assert fln._pick_rows(32768, 768, BWD_BF16) == 256   # narrow: full block
    rows_4k = fln._pick_rows(32768, 4096, BWD_BF16)
    assert rows_4k <= 136 and rows_4k % 8 == 0           # ~12MB/22B/4096
    # fp32 inputs carry a bigger footprint -> smaller blocks (review r5)
    assert fln._pick_rows(32768, 4096, BWD_F32) < rows_4k
    assert fln._pick_rows(32768, 16384, BWD_F32) >= 8    # floor
    assert fln._pick_rows(4, 768, BWD_BF16) == 4         # never exceeds n1


def test_kernel_max_width_tracks_itemsize():
    """The max-width gate derives from the actual input itemsize
    (ADVICE r5): the 8-row floor block must fit the VMEM budget for
    EVERY admitted width, including dtypes wider than fp32."""
    import apex_tpu.normalization.fused_layer_norm  # noqa: F401
    fln = sys.modules["apex_tpu.normalization.fused_layer_norm"]
    for isz in (2, 4, 8):                    # bf16, fp32, fp64
        w = fln._kernel_max_width(isz)
        floor_bytes = (3 * isz + 16) * 8 * w
        assert floor_bytes <= fln._VMEM_BUDGET_BYTES, \
            f"itemsize {isz}: floor block {floor_bytes / 1e6:.1f} MB " \
            f"exceeds the budget at admitted width {w}"
        # one column wider must be rejected (the gate is tight)
        assert (3 * isz + 16) * 8 * (w + 1) > fln._VMEM_BUDGET_BYTES
    # wider itemsize -> narrower gate; the old fp32 constant is the default
    assert fln._kernel_max_width(8) < fln._kernel_max_width(4) \
        < fln._kernel_max_width(2)
    assert fln._KERNEL_MAX_WIDTH == fln._kernel_max_width(4)
    # dispatch honors the per-itemsize gate: an fp64 width that passed
    # the old fp32-tuned constant is now routed to jnp (no legal block)
    orig = fln._use_pallas
    fln._use_pallas = lambda: True
    try:
        wide = fln._kernel_max_width(8) + 8       # legal for fp32...
        assert fln._dispatch_pallas(8, wide, "pallas", itemsize=4)
        assert not fln._dispatch_pallas(8, wide, "pallas", itemsize=8)
    finally:
        fln._use_pallas = orig
