"""ImageNet training with apex_tpu amp — the TPU port of the reference
entry point (``examples/imagenet/main_amp.py``): same CLI surface
(--arch/--opt-level/--keep-batchnorm-fp32/--loss-scale/--sync_bn/-b/--lr...),
TPU-native mechanics (one jitted SPMD train step over a device mesh instead
of hooks + NCCL; bf16 instead of fp16).

The training loop runs on :class:`apex_tpu.runtime.StepPipeline`:
``--steps-per-call K`` chains K steps into ONE compiled program, batch
windows are staged on device through the prefetcher (H2D of window N+1
overlaps the device loop of window N — the reference ``data_prefetcher``'s
stream overlap, at window granularity), and metric prints read one
dispatch behind so the hot loop never drains the pipeline on a scalar.

Data: pass an ImageNet directory laid out as class subfolders of npy/JPEG
files, or use --synthetic (default when no dir is given) for generated
data.  The normalize epilogue (native C++) and threaded device prefetch
are identical either way; JPEG decode itself is PIL on a thread pool —
functional, but not a DALI-class engine (the reference uses DALI for
full-rate ImageNet) — so .npy or --synthetic are the benchmarked paths.

Run (single chip or full pod — same command, SPMD handles both):
    python main_amp.py --synthetic -b 128 --opt-level O2 [--sync_bn]
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import runtime, training
from apex_tpu.parallel import import_shard_map

shard_map = import_shard_map()
from apex_tpu.data import normalize_images, synthetic_imagenet
from apex_tpu.models import (ResNet18, ResNet34, ResNet50, ResNet101,
                             ResNet152)
from apex_tpu.training import make_train_step

ARCHS = {"resnet18": ResNet18, "resnet34": ResNet34, "resnet50": ResNet50,
         "resnet101": ResNet101, "resnet152": ResNet152}


def parse():
    p = argparse.ArgumentParser(description="apex_tpu ImageNet Training")
    p.add_argument("data", nargs="?", default=None, help="path to dataset")
    p.add_argument("--arch", "-a", default="resnet18", choices=sorted(ARCHS))
    p.add_argument("--epochs", default=90, type=int)
    p.add_argument("-b", "--batch-size", default=256, type=int,
                   help="GLOBAL batch size (split over the mesh)")
    p.add_argument("--lr", "--learning-rate", default=0.1, type=float)
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--weight-decay", "--wd", default=1e-4, type=float)
    p.add_argument("--print-freq", "-p", default=10, type=int)
    p.add_argument("--prof", default=-1, type=int,
                   help="stop after N iterations (profiling); on "
                        "synthetic runs with a device loop, best-window "
                        "timing then adds 6 extra calls (3 windows x 2 "
                        "calls, reusing one synthetic batch) beyond this "
                        "budget")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--sync_bn", action="store_true")
    p.add_argument("--opt-level", type=str, default="O0")
    p.add_argument("--keep-batchnorm-fp32", type=str, default=None)
    p.add_argument("--loss-scale", type=str, default=None)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--image-size", default=224, type=int)
    p.add_argument("--steps-per-epoch", default=100, type=int)
    p.add_argument("--steps-per-call", default=1, type=int,
                   help="chain N train steps into ONE compiled program "
                   "(apex_tpu.runtime.StepPipeline) — the TPU device-loop "
                   "shape; host dispatch and metric fetches then cost "
                   "once per N steps.  Real-data runs stage stacked "
                   "windows through the prefetcher (H2D overlaps the "
                   "device loop); a ragged final window is padded and "
                   "mask-gated on device, no retrace.")
    return p.parse_args()


def main():
    args = parse()
    print("opt_level =", args.opt_level)
    if args.deterministic:
        jax.config.update("jax_default_matmul_precision", "highest")

    devices = jax.devices()
    n_dev = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    if args.batch_size % n_dev:
        raise SystemExit(f"global batch {args.batch_size} must divide over "
                         f"{n_dev} devices")
    # Reference lr scaling: lr * global_batch/256 (main_amp.py --lr help).
    lr = args.lr * args.batch_size / 256.0

    dtype = (jnp.bfloat16 if args.opt_level in ("O1", "O2", "O3")
             else jnp.float32)
    model_cls = ARCHS[args.arch]
    model = model_cls(num_classes=1000, dtype=dtype,
                      sync_bn=args.sync_bn,
                      axis_name="data" if args.sync_bn else None)
    init_model = model_cls(num_classes=1000, dtype=dtype)

    x0 = jnp.ones((2, args.image_size, args.image_size, 3), jnp.float32)
    variables = init_model.init(jax.random.PRNGKey(0), x0, train=True)
    if args.sync_bn:
        # init_model uses plain BatchNorm (SyncBatchNorm's collectives
        # need the mesh, absent at init); adopt its stats under the sync
        # module's names so the batch_stats pytree is structure-stable —
        # the K-step scan carry requires it.
        from apex_tpu.parallel import adopt_batchnorm_stats
        variables = dict(
            variables,
            batch_stats=adopt_batchnorm_stats(variables["batch_stats"]))

    def loss_fn(p, ms, batch):
        xb, yb = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    loss_scale = args.loss_scale
    if loss_scale is not None and loss_scale != "dynamic":
        loss_scale = float(loss_scale)
    keep_bn = args.keep_batchnorm_fp32
    if isinstance(keep_bn, str):
        keep_bn = keep_bn == "True"

    tx = training.sgd(lr=lr, momentum=args.momentum,
                      weight_decay=args.weight_decay)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=args.opt_level, loss_scale=loss_scale,
        keep_batchnorm_fp32=keep_bn, axis_name="data",
        has_model_state=True)
    state = init_fn(variables["params"], variables["batch_stats"])

    spc = max(1, args.steps_per_call)
    if spc > 1 and args.prof > 0 and args.prof % spc:
        # The device loop advances spc steps per call; honor --prof at
        # call granularity rather than silently overrunning it.
        rounded = ((args.prof + spc - 1) // spc) * spc
        print(f"note: --prof {args.prof} rounded up to {rounded} "
              f"(multiple of --steps-per-call {spc})")
        args.prof = rounded
    if spc > 1 and args.print_freq % spc:
        # Same granularity rule for printing: the cadence below floors
        # print_freq to whole calls, so a print_freq < spc would silently
        # print (and pay the metric fetch) on EVERY call (ADVICE r4).
        rounded = ((args.print_freq + spc - 1) // spc) * spc
        print(f"note: --print-freq {args.print_freq} rounded up to "
              f"{rounded} (multiple of --steps-per-call {spc})")
        args.print_freq = rounded

    synthetic = args.synthetic or args.data is None
    # The device loop: spc steps per program over a [spc, batch, ...]
    # window.  The window's leading (step) axis stays unsharded; the
    # per-step batch axis shards over the mesh; the tail-mask is
    # replicated.  Streaming (real-data) windows are fresh buffers and
    # get donated with the state; the synthetic pool window is reused
    # every call, so it must not be.
    pipe = runtime.StepPipeline(
        step_fn, spc,
        wrap=lambda fn: shard_map(
            fn, mesh=mesh,
            in_specs=(P(), (P(None, "data"), P(None, "data")), P()),
            out_specs=(P(), P())),
        donate_window=not synthetic)

    data_sh = NamedSharding(mesh, P(None, "data"))
    if synthetic:
        # Synthetic data: pre-upload ONE stacked window and cycle it
        # device-side.  Streaming per-step synthetic batches would
        # measure host->device bandwidth (77 MB/step at b128/224), not
        # training — the reference's synthetic smoke does the same with
        # a single static batch.  Real-data runs below stage fresh
        # windows through the threaded prefetcher instead.
        pool_n = 8
        pool = []
        for imgs, labels in synthetic_imagenet(args.batch_size,
                                               args.image_size,
                                               steps=pool_n):
            pool.append((normalize_images(imgs),
                         np.asarray(labels, np.int32)))
        stack = jax.device_put(
            jax.tree_util.tree_map(
                lambda *xs: np.stack(xs),
                *(pool[i % pool_n] for i in range(spc))),
            data_sh)
        total = args.steps_per_epoch * args.epochs
        windows = ((stack, spc) for _ in range(0, total, spc))
    else:
        from apex_tpu.data import directory_imagenet
        stream = directory_imagenet(args.data, args.batch_size,
                                    args.image_size)
        windows = runtime.stage_windows(
            stream, spc,
            transform=lambda b: (normalize_images(b[0]),
                                 np.asarray(b[1], np.int32)),
            device=data_sh)

    t0 = time.perf_counter()
    reader = runtime.DeferredMetrics()
    print_every = max(1, args.print_freq // spc)   # cadence in WINDOWS

    def emit(wm):
        """Print one window's iter line from its stacked metrics — ONE
        device->host transfer per print, one dispatch behind the loop."""
        vals = wm.fetch()
        last = wm.n_valid - 1
        loss = float(np.ravel(vals["loss"])[last])
        scale = float(np.ravel(vals["loss_scale"])[last])
        done = wm.step + wm.n_valid
        ips = args.batch_size * done / (time.perf_counter() - t0)
        print(f"iter {done - 1}  loss {loss:.4f}  "
              f"speed {ips:.1f} img/s  loss_scale {scale:.0f}")

    t1 = None
    warm = 0
    printed = -1        # window index of the last emitted print
    window = None
    for ci, (window, n_valid) in enumerate(windows):
        if args.prof >= 0 and reader.steps_pushed >= args.prof:
            break
        state, metrics = pipe.step_window(state, window, n_valid)
        prev = reader.push(metrics, n_valid)
        if ci <= 1:
            # Calls 0 AND 1 both compile: call 0 the initial trace, call 1
            # a re-specialization because the donated state returns with
            # the mesh's NamedSharding (jit caches on input shardings).
            # Drain them synchronously so the steady clock starts after
            # both (the reference's AverageMeter skips warmup the same
            # way).
            reader.newest().fetch()
            t1 = time.perf_counter()
            warm = reader.steps_pushed
        if prev is not None and (prev.step // spc) % print_every == 0:
            emit(prev)
            printed = prev.step // spc
    if hasattr(windows, "close"):
        # --prof break abandons the stream mid-epoch: release the
        # prefetch producer thread and its staged device windows now
        # rather than at GC time (no-op after normal exhaustion, and on
        # the synthetic generator).
        windows.close()
    n_done = reader.steps_pushed
    newest = reader.newest()
    if newest is not None and (newest.step // spc) % print_every == 0 \
            and newest.step // spc > printed:
        emit(newest)     # the fetch doubles as the end-of-loop drain
    else:
        # force completion before stopping the clock (block_until_ready
        # is a no-op on the tunnel; the stacked metric fetch drains the
        # enqueued pipeline)
        reader.last()
    if n_done > warm and t1 is not None:
        steady = (args.batch_size * (n_done - warm)
                  / (time.perf_counter() - t1))
        # "first 2 calls", not "N compile iters": under the device loop
        # the excluded window is 2*spc steps but only the two compiling
        # CALLS, not 2*spc compile iterations (ADVICE r4).
        print(f"steady {steady:.1f} img/s over {n_done - warm} iters "
              f"(excl first 2 calls)")
    # spc > 1 only: at one step per call the 2-call window is bounded by
    # the fixed metric-fetch round-trip (~0.5 s on the tunnel), so the
    # "best window" would measure fetch latency, not training.
    if synthetic and n_done > warm and spc > 1 and window is not None:
        # Best-of-3 windows (the repo's min-of-reps policy, like the
        # DCGAN example): one steady window can eat a multi-second
        # tunnel stall that has nothing to do with training throughput.
        # Each window = 2 calls (2*spc steps) synced by one metric
        # fetch, so the fixed fetch round-trip amortizes over the
        # window; the best window is what the chip demonstrably does.
        best = float("inf")
        for _ in range(3):
            t0w = time.perf_counter()
            for _ in range(2):
                state, metrics = pipe.step_window(state, window, spc)
            runtime.WindowMetrics(0, spc, metrics).fetch()
            best = min(best, time.perf_counter() - t0w)
        print(f"best-window {args.batch_size * 2 * spc / best:.1f} img/s "
              f"over {2 * spc}-iter windows")
    print("done")


if __name__ == "__main__":
    main()
