"""Serve a GPT causal LM with the apex_tpu serving engine (ISSUE 11).

No reference counterpart (apex is training-only); this is the
deployment-shaped driver of ``apex_tpu.serving``: AOT-bucketed
prefill/decode (zero steady-state compiles), continuous batching over
the paged KV cache, optional weight hot-swap from a training job's
checkpoint directory, and the live ``serving_*`` gauges through
``--telemetry`` / ``--metrics-port``.

    python serve_lm.py --requests 16 --max-new 16
    python serve_lm.py --checkpoint-dir /ckpts --watch --telemetry s.jsonl
    python serve_lm.py --requests 64 --buckets 128,256 --max-seqs 8
    python serve_lm.py --telemetry s.jsonl --trace-sample 1 \
        --slo 'ttft_p99<200ms,tpot_p99<30ms'
    # then: python -m apex_tpu.prof.requests s.jsonl --slo ...
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import argparse
import time

import jax
import numpy as np

from apex_tpu import serving, telemetry
from apex_tpu.checkpoint import load_checkpoint_dir
from apex_tpu.models import gpt_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8,
                    help="closed-loop load: this many synthetic prompts")
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--buckets", default="64,128",
                    help="comma-separated sequence-length buckets")
    ap.add_argument("--max-seqs", type=int, default=4,
                    help="decode batch width (concurrent sequences)")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="load initial weights from the newest valid "
                         "checkpoint here (a raw params-tree save)")
    ap.add_argument("--watch", action="store_true",
                    help="keep watching --checkpoint-dir and hot-swap "
                         "newly committed checkpoints with zero downtime")
    ap.add_argument("--telemetry", default=None,
                    help="JSONL stream path (env APEX_TPU_TELEMETRY)")
    ap.add_argument("--trace-sample", type=int, default=None,
                    metavar="N",
                    help="trace every Nth request as a span tree in the "
                         "telemetry stream (0/unset = off; env "
                         "APEX_TPU_TRACE_SAMPLE); analyze with "
                         "python -m apex_tpu.prof.requests")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="serve against a latency SLO, e.g. "
                         "'ttft_p99<200ms,tpot_p99<30ms' (env "
                         "APEX_TPU_SLO): live goodput/burn-rate gauges "
                         "+ slo_burn/slo_exhausted watchdog alerts")
    args = ap.parse_args()

    rec = None
    if args.telemetry or (_os.environ.get("APEX_TPU_TELEMETRY") or "").strip():
        rec = telemetry.start(args.telemetry, watchdog=True,
                              example="serve_lm",
                              trace_sample_n=args.trace_sample,
                              slo=args.slo)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = gpt_tiny(max_len=max(buckets))
    rng = np.random.RandomState(args.seed)
    probe = rng.randint(1, 1024, (1, 8))
    params = model.init(jax.random.PRNGKey(0),
                        jax.numpy.asarray(probe))["params"]
    start_step = None
    if args.checkpoint_dir:
        restored = load_checkpoint_dir(args.checkpoint_dir, params)
        params, start_step = restored.state, restored.step
        print(f"loaded checkpoint step {restored.step} "
              f"from {args.checkpoint_dir}")

    eng = serving.ServingEngine(
        model, params, buckets=buckets, page_size=args.page_size,
        max_seqs=args.max_seqs,
        watch_dir=args.checkpoint_dir if args.watch else None,
        watch_from_step=start_step)
    try:
        t0 = time.perf_counter()
        eng.warmup()
        print(f"warmup: {len(buckets)} bucket(s) AOT-compiled in "
              f"{time.perf_counter() - t0:.1f}s")
        prompts = [rng.randint(1, 1024, (int(n),))
                   for n in rng.randint(4, max(buckets) - args.max_new,
                                        args.requests)]
        t0 = time.perf_counter()
        results = eng.generate(prompts, max_new_tokens=args.max_new)
        wall = time.perf_counter() - t0
        ok = [r for r in results if r.ok]
        lats = sorted(r.timings["total_s"] for r in ok)
        p99 = lats[min(len(lats) - 1, int(0.99 * (len(lats) - 1)))]
        print(f"served {len(ok)}/{len(results)} requests, "
              f"{eng.stats['tokens_out']} tokens in {wall:.2f}s "
              f"({eng.stats['tokens_out'] / wall:.1f} tok/s), "
              f"p99 latency {p99 * 1e3:.1f} ms, "
              f"aot_misses {eng.stats['aot_misses']}, "
              f"rejected {eng.stats['rejected']}, "
              f"hotswaps {eng.stats['hotswaps']}")
        # per-request latency split (ISSUE 20): TTFT is what an
        # interactive caller feels, TPOT is the streaming rate after it
        ttfts = sorted(r.timings["ttft_s"] for r in ok
                       if r.timings.get("ttft_s") is not None)
        tpots = sorted(r.timings["tpot_s"] for r in ok
                       if r.timings.get("tpot_s") is not None)
        if ttfts:
            def _p(v, q):
                return v[min(len(v) - 1, int(q * (len(v) - 1)))] * 1e3
            print(f"ttft p50 {_p(ttfts, 0.5):.1f} / "
                  f"p99 {_p(ttfts, 0.99):.1f} ms"
                  + (f", tpot p50 {_p(tpots, 0.5):.2f} / "
                     f"p99 {_p(tpots, 0.99):.2f} ms" if tpots else ""))
    finally:
        eng.close()
        if rec is not None:
            slo_eng = rec.slo
            rec.close()
            if slo_eng is not None and slo_eng.last is not None:
                print("slo:", slo_eng.format_line())
            if rec.watchdog is not None:
                print("health:", rec.watchdog.format_line())
            if args.telemetry and args.trace_sample:
                print(f"traces: python -m apex_tpu.prof.requests "
                      f"{args.telemetry}"
                      + (f" --slo '{args.slo}'" if args.slo else ""))


if __name__ == "__main__":
    main()
