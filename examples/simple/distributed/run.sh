#!/bin/bash
# Reference: torch.distributed.launch --nproc_per_node=2 ... (run.sh).
# TPU-native: SPMD sees every chip in one process — no launcher needed.
# To simulate a multi-device run on CPU:
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu bash run.sh
python "$(dirname "$0")/distributed_data_parallel.py"
