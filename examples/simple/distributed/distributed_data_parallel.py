"""Minimal distributed amp example — the TPU port of the reference
``examples/simple/distributed/distributed_data_parallel.py``.

The reference choreography (torch.distributed.launch → N processes →
init_process_group('nccl') → amp.initialize → DDP(model) → hooks allreduce
during backward) becomes ONE SPMD program: jax sees every chip, shard_map
splits the batch over the mesh, and the DDP contract (grads averaged across
replicas by step time) is satisfied by `reduce_gradients` inside the jitted
step.  Run the same script on 1 chip or a pod — no launcher needed:

    python distributed_data_parallel.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 3)))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu import training
from apex_tpu.training import make_train_step

N, D_in, D_out = 64, 1024, 16


def main():
    devices = jax.devices()
    if _os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Honor an explicit CPU request even when an accelerator plugin
        # keeps itself registered as the default backend (so the
        # 8-virtual-device CPU-mesh recipe in the README works anywhere).
        devices = jax.devices("cpu")
        jax.config.update("jax_default_device", devices[0])
    world = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    print(f"world size {world} ({devices[0].platform})")

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N * max(world, 1), D_in), jnp.float32)
    y = jnp.asarray(rng.randn(N * max(world, 1), D_out), jnp.float32)
    params = {"w": jnp.asarray(rng.randn(D_in, D_out) * 0.01, jnp.float32),
              "b": jnp.zeros((D_out,), jnp.float32)}

    def loss_fn(p, batch):
        xb, yb = batch
        pred = xb @ p["w"].astype(xb.dtype) + p["b"].astype(xb.dtype)
        return jnp.mean((pred.astype(jnp.float32) - yb) ** 2)

    # O1: params stay fp32; the autocast policy runs the matmul in bf16.
    init_fn, step_fn = make_train_step(loss_fn, training.sgd(lr=1e-3),
                                       opt_level="O1", axis_name="data")
    state = init_fn(params)
    step = jax.jit(shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), (P("data"), P("data"))), out_specs=(P(), P())),
        donate_argnums=(0,))

    for t in range(500):
        state, metrics = step(state, (x, y))
        if t % 100 == 0:
            # jaxlint: disable=J001 -- print-frequency-gated: one fetch per 100 steps, the demo's progress contract
            print(f"step {t}  loss {float(metrics['loss']):.6f}")

    print("final loss", float(metrics["loss"]))


if __name__ == "__main__":
    main()
