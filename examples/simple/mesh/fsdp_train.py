"""DP×FSDP training on the mesh frontend — declare once, derive all.

The :class:`apex_tpu.parallel.mesh.MeshPlan` showcase (ISSUE 12): one
declaration of the mesh (``--dp``/``--fsdp``, default pure FSDP over
every device) derives the batch sharding, the ZeRO state partitioning
(``--zero 2`` shards optimizer state; ``--zero 3`` shards the params
themselves as flat buckets, gathered per-bucket inside the step), the
AOT-warmed pipelined hot loop, and the elastic checkpoint layout — the
same script drives 1 chip, an 8-device CPU mesh, or a pod:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python fsdp_train.py --zero 3 --steps 32

    # multi-host: one process per host, env from the launcher
    python -m apex_tpu.parallel.multiproc --nproc 2 fsdp_train.py --zero 3
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 3)))

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import runtime, training
from apex_tpu.parallel import mesh, multiproc

D_in, D_hidden, D_out = 256, 512, 64


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=None,
                    help="data-parallel axis size (default: 1)")
    ap.add_argument("--fsdp", type=int, default=None,
                    help="state-sharding axis size (default: all devices)")
    ap.add_argument("--zero", type=int, default=2, choices=(1, 2, 3))
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16,
                    help="per-data-shard batch size")
    ap.add_argument("--opt-level", default="O2")
    ap.add_argument("--rank", type=int, default=None,
                    help="(set by the multiproc launcher; env wins)")
    args = ap.parse_args(argv)

    # Multi-host: a no-op single-process unless the launcher env is set.
    pid, nproc = multiproc.initialize()

    devices = jax.devices()
    if _os.environ.get("JAX_PLATFORMS", "") == "cpu" and nproc == 1:
        # Single-process CPU-mesh recipe; under multi-host jax.devices()
        # already spans every process and the default device must stay
        # a LOCAL one.
        devices = jax.devices("cpu")
        jax.config.update("jax_default_device", devices[0])
    if args.fsdp is None and args.dp is None:
        plan = mesh.MeshPlan.auto(devices=devices)
    else:
        dp = args.dp or 1
        fsdp = args.fsdp or len(devices) // dp
        plan = mesh.MeshPlan(dp=dp, fsdp=fsdp,
                             devices=devices[:dp * fsdp])
    if multiproc.is_coordinator():
        print(f"{plan} zero={args.zero} opt_level={args.opt_level} "
              f"process {pid}/{nproc}")

    rng = np.random.RandomState(0)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(D_in, D_hidden) * 0.05,
                                jnp.float32),
               "b": jnp.zeros((D_hidden,), jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(D_hidden, D_out) * 0.05,
                                jnp.float32),
               "b": jnp.zeros((D_out,), jnp.float32)},
    }

    def loss_fn(p, batch):
        x, y = batch
        h = jax.nn.relu(x @ p["l1"]["w"].astype(x.dtype)
                        + p["l1"]["b"].astype(x.dtype))
        pred = h @ p["l2"]["w"].astype(x.dtype) + p["l2"]["b"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    ms = mesh.make_mesh_train_step(loss_fn, training.adam(1e-3), plan,
                                   zero=args.zero,
                                   opt_level=args.opt_level,
                                   loss_scale="dynamic")
    state = ms.init(params)
    if multiproc.is_coordinator():
        led = plan.state_bytes((state.params, state.opt_state))
        print(f"state: {led['global_bytes'] / 1e6:.2f} MB global, "
              f"{led['bytes_per_device'] / 1e6:.2f} MB/device "
              f"(ratio {led['ratio']})")

    K = args.steps_per_call
    pipe = runtime.StepPipeline(ms.step_fn, K, wrap=ms.pipeline_wrap(state))
    # each data shard sees its own stream; the K axis stays unsharded
    local_rows = args.batch * plan.data_world // max(nproc, 1)

    def batches():
        r = np.random.RandomState(1 + pid)
        for _ in range(args.steps):
            yield (r.randn(local_rows, D_in).astype(np.float32),
                   r.randn(local_rows, D_out).astype(np.float32) * 0.1)

    windows = [(plan.device_put_window(w), n) for w, n in
               runtime.window_batches(batches(), K)]
    pipe.warmup(state, windows[0][0])        # AOT: sharded, zero retraces
    reader = runtime.DeferredMetrics()
    for window, n_valid in windows:
        state, metrics = pipe.step_window(state, window, n_valid)
        prev = reader.push(metrics, n_valid)
        if prev is not None and multiproc.is_coordinator():
            host = prev.fetch()
            print(f"step {prev.step:4d}  loss "
                  f"{float(np.ravel(host['loss'])[0]):.6f}")  # jaxlint: disable=J001 -- DeferredMetrics contract: one batched fetch, one dispatch behind the hot loop
    final = reader.last()
    if multiproc.is_coordinator():
        print(f"final loss {float(np.ravel(final['loss'])[-1]):.6f}")
        print("done")


if __name__ == "__main__":
    main()
