"""DCGAN with amp — the TPU port of the reference
``examples/dcgan/main_amp.py:214-253``: two models, two optimizers, THREE
losses with separate loss scalers (``amp.initialize(..., num_losses=3)``,
``loss_id=0/1/2``), exercised through the imperative amp surface.

    python main_amp.py --niter 1 --batchSize 64 --opt_level O1
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.models import Generator, Discriminator
from apex_tpu.optimizers import FusedAdam


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--batchSize", type=int, default=64)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--niter", type=int, default=1)
    p.add_argument("--iters-per-epoch", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.0002)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--opt_level", type=str, default="O1")
    p.add_argument("--print-freq", type=int, default=1,
                   help="print losses every N iters (0 = only the final "
                   "iter); each print forces device->host loss fetches, "
                   "whole round-trips on a tunneled chip")
    p.add_argument("--data-pool", type=int, default=8,
                   help="pre-staged synthetic batches reused cyclically "
                   "(host->device upload happens before the timed loop, "
                   "like a prefetching input pipeline)")
    p.add_argument("--warmup", type=int, default=2,
                   help="iters excluded from the steady-state rate "
                   "(jit compiles happen in the first iterations)")
    return p.parse_args()


def bce_with_logits(logits, target):
    z = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(z, 0) - z * target
                    + jnp.log1p(jnp.exp(-jnp.abs(z))))


def main():
    opt = parse()
    key = jax.random.PRNGKey(0)
    netG = Generator(ngf=opt.ngf, nc=3)
    netD = Discriminator(ndf=opt.ndf)

    z0 = jnp.ones((opt.batchSize, opt.nz))
    gv = netG.init(key, z0)
    img0 = netG.apply(gv, z0, train=False)
    dv = netD.init(jax.random.PRNGKey(1), img0)

    optimizerG = FusedAdam(gv["params"], lr=opt.lr, betas=(opt.beta1, 0.999))
    optimizerD = FusedAdam(dv["params"], lr=opt.lr, betas=(opt.beta1, 0.999))

    # Multi-model / multi-optimizer / multi-loss init (reference
    # main_amp.py:214-215).
    [gp, dp], [optimizerG, optimizerD] = amp.initialize(
        [optimizerG.params, optimizerD.params], [optimizerG, optimizerD],
        opt_level=opt.opt_level, num_losses=3)

    g_state = {k: v for k, v in gv.items() if k != "params"}
    d_state = {k: v for k, v in dv.items() if k != "params"}
    real_label, fake_label = 1.0, 0.0

    def d_loss_real(d_params, real):
        out, _ = netD.apply({"params": d_params, **d_state}, real,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    def d_loss_fake(d_params, fake):
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, fake_label)

    def g_loss(g_params, d_params, noise):
        fake, _ = netG.apply({"params": g_params, **g_state}, noise,
                             train=True, mutable=["batch_stats"])
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    # jit the three grad computations once — the amp O1 policy is a
    # trace-time decision, so compiled steps see the same cast policy.
    vg_d_real = jax.jit(optimizerD.value_and_grad(d_loss_real))
    vg_d_fake = jax.jit(optimizerD.value_and_grad(d_loss_fake))
    gen = jax.jit(lambda gp_, n: netG.apply(
        {"params": gp_, **g_state}, n, train=True,
        mutable=["batch_stats"])[0])
    vg_g = jax.jit(optimizerG.value_and_grad(g_loss))

    # Pre-staged synthetic batches: upload ONCE before the timed loop and
    # cycle through them — the imperative loop then measures the amp
    # machinery, not host RNG + host->device streaming (tens of MB/s on a
    # tunneled chip).  The reference gets the same effect from DALI/
    # DataLoader prefetch (examples/dcgan/main_amp.py:214-253 consumes a
    # pre-built dataloader).
    rng = np.random.RandomState(0)
    pool = [(jnp.asarray(rng.randn(opt.batchSize, 64, 64, 3) * 0.5,
                         jnp.float32),
             jnp.asarray(rng.randn(opt.batchSize, opt.nz), jnp.float32))
            for _ in range(max(1, opt.data_pool))]

    t0 = time.perf_counter()
    total = opt.niter * opt.iters_per_epoch
    t_steady = t0 if opt.warmup <= 0 else None
    it = 0
    for epoch in range(opt.niter):
        for i in range(opt.iters_per_epoch):
            real, noise = pool[it % len(pool)]

            # (1) D on real, loss_id=0
            errD_real, gD = vg_d_real(real)
            with amp.scale_loss(errD_real, optimizerD, loss_id=0):
                optimizerD.backward(gD)
            # (1b) D on fake (G detached: only D grads), loss_id=1
            fake = gen(optimizerG.params, noise)
            errD_fake, gDf = vg_d_fake(fake)
            with amp.scale_loss(errD_fake, optimizerD, loss_id=1):
                optimizerD.backward(gDf)
            optimizerD.step()

            # (2) G, loss_id=2 (grads w.r.t. G through D)
            errG, gG = vg_g(optimizerD.params, noise)
            with amp.scale_loss(errG, optimizerG, loss_id=2):
                optimizerG.backward(gG)
            optimizerG.step()

            it += 1
            if it == opt.warmup and it < total:
                t_steady = time.perf_counter()     # compiles are behind us
            if (opt.print_freq > 0 and it % opt.print_freq == 0) \
                    or it == total:
                # the float() fetches force execution (and pay tunnel
                # round-trips) — gate them behind print-freq
                errD = float(errD_real) + float(errD_fake)
                print(f"[{epoch}/{opt.niter}][{i}/{opt.iters_per_epoch}] "
                      f"Loss_D: {errD:.4f} Loss_G: {float(errG):.4f}")
    float(errG)                                    # drain the pipeline
    t1 = time.perf_counter()
    if t_steady is not None and total > opt.warmup:
        n_steady = total - opt.warmup
        print(f"steady {n_steady / (t1 - t_steady):.2f} it/s over "
              f"{n_steady} iters (excl {opt.warmup} warmup)")
    print(f"done in {t1 - t0:.1f}s ({total / (t1 - t0):.2f} it/s)")


if __name__ == "__main__":
    main()
