"""DCGAN with amp — the TPU port of the reference
``examples/dcgan/main_amp.py:214-253``: two models, two optimizers, THREE
losses with separate loss scalers.

Two modes:

* default — the step-pipelined path: the whole iteration (G forward,
  both D backwards, D update, G backward, G update, all three dynamic
  loss-scale machines) compiles into ONE program, and
  :class:`apex_tpu.runtime.StepPipeline` chains ``--steps-per-call`` of
  them per host dispatch with losses read back one dispatch behind.
  This is the three-scaler stress test for the runtime: every scaler's
  overflow flag stays a device-side select inside the scan carry.
  (BENCH r05 measured the old imperative loop at 4.67 it/s steady
  against 57 it/s best-window — 10 host dispatches per iteration; the
  pipelined program is one dispatch per K iterations.)
* ``--imperative`` — the reference-parity surface (``amp.initialize(...,
  num_losses=3)``, ``scale_loss(loss_id=0/1/2)``, ``FusedAdam.step()``),
  exercised through the imperative API exactly as the reference example
  drives it.

    python main_amp.py --niter 1 --batchSize 64 --opt_level O1
    python main_amp.py --niter 1 --imperative
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp, runtime, training
from apex_tpu.models import Generator, Discriminator
from apex_tpu.optimizers import FusedAdam


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--batchSize", type=int, default=64)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--niter", type=int, default=1)
    p.add_argument("--iters-per-epoch", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.0002)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--opt_level", type=str, default="O1")
    p.add_argument("--print-freq", type=int, default=1,
                   help="print losses every N iters (0 = only the final "
                   "iter); pipelined mode rounds the cadence to whole "
                   "windows and reads one dispatch behind, so a print "
                   "never drains the pipeline")
    p.add_argument("--data-pool", type=int, default=8,
                   help="pre-staged synthetic batches reused cyclically "
                   "(host->device upload happens before the timed loop, "
                   "like a prefetching input pipeline)")
    p.add_argument("--warmup", type=int, default=4,
                   help="iters excluded from the steady-state rate (the "
                   "first iterations compile; the SECOND call of each "
                   "program can retrace too — jit caches on input "
                   "shardings, and step outputs come back committed)")
    p.add_argument("--steps-per-call", type=int, default=8,
                   help="pipelined mode: chain N whole GAN iterations "
                   "(D phase + G phase + 3 scaler updates) into ONE "
                   "compiled program via apex_tpu.runtime.StepPipeline")
    p.add_argument("--imperative", action="store_true",
                   help="run the reference-parity imperative amp surface "
                   "(amp.initialize num_losses=3 + scale_loss loss_id + "
                   "FusedAdam.step) instead of the pipelined runtime")
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   metavar="DIR",
                   help="async sharded checkpointing of the full GAN "
                        "state (both parameter trees, both Adam states, "
                        "all three scalers) every --checkpoint-every "
                        "iters at window boundaries (pipelined mode)")
    p.add_argument("--checkpoint-every", type=int, default=100,
                   help="save cadence in iters (window-boundary floored)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint under "
                        "--checkpoint-dir (pipelined mode)")
    p.add_argument("--drain", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="graceful SIGTERM/SIGINT drain (ON by default): "
                        "finish the window, write a final checkpoint, "
                        "flush the recorder; second signal hard-stops")
    p.add_argument("--telemetry", type=str, default=_os.environ.get(
                       "APEX_TPU_TELEMETRY") or None, metavar="PATH",
                   help="record the run-telemetry event stream (JSONL) "
                   "to PATH; analyze offline with "
                   "python -m apex_tpu.prof.timeline PATH.  Defaults "
                   "from APEX_TPU_TELEMETRY")
    p.add_argument("--metrics-port", type=int, metavar="PORT",
                   default=(int(_os.environ["APEX_TPU_METRICS_PORT"])
                            if _os.environ.get("APEX_TPU_METRICS_PORT")
                            else None),
                   help="serve live Prometheus metrics on "
                   "http://:PORT/metrics (0 = ephemeral; defaults from "
                   "APEX_TPU_METRICS_PORT)")
    p.add_argument("--metrics-textfile", metavar="PATH",
                   default=_os.environ.get("APEX_TPU_METRICS_TEXTFILE")
                   or None,
                   help="atomically-replaced Prometheus textfile for "
                   "node-exporter scraping (defaults from "
                   "APEX_TPU_METRICS_TEXTFILE)")
    p.add_argument("--watchdog", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="run-health rule engine over the telemetry "
                   "events (debounced alerts + a health: line at exit); "
                   "ON by default when --telemetry is set, "
                   "--no-watchdog disables")
    return p.parse_args()


def bce_with_logits(logits, target):
    z = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(z, 0) - z * target
                    + jnp.log1p(jnp.exp(-jnp.abs(z))))


def _build_models(opt, key):
    netG = Generator(ngf=opt.ngf, nc=3)
    netD = Discriminator(ndf=opt.ndf)
    z0 = jnp.ones((opt.batchSize, opt.nz))
    gv = netG.init(key, z0)
    img0 = netG.apply(gv, z0, train=False)
    dv = netD.init(jax.random.PRNGKey(1), img0)
    return netG, netD, gv, dv


def _synthetic_pool(opt):
    """Pre-staged synthetic batches, uploaded ONCE before the timed loop
    and cycled — the loop then measures the amp machinery, not host RNG
    + host->device streaming (tens of MB/s on a tunneled chip).  The
    reference gets the same effect from DALI/DataLoader prefetch.

    "Real" images come from the native counter-based generator
    (ISSUE 3: zero Python-RNG time on the producer side), normalized to
    roughly zero-mean; only the small [batch, nz] noise stays np.random
    (the generator consumes float gaussians)."""
    from apex_tpu.data import synthetic_imagenet

    rng = np.random.RandomState(0)
    imgs = [im for im, _ in synthetic_imagenet(
        opt.batchSize, 64, steps=max(1, opt.data_pool))]
    return [(jnp.asarray((im.astype(np.float32) / 255.0 - 0.5),
                         jnp.float32),
             jnp.asarray(rng.randn(opt.batchSize, opt.nz), jnp.float32))
            for im in imgs]


# -- pipelined mode: one program per K iterations -----------------------------

def main_pipelined(opt):
    """The runtime path: a pure ``step_fn(state, batch)`` carrying BOTH
    parameter trees, both Adam states, and all three dynamic loss-scale
    states; :class:`runtime.StepPipeline` scans it K iterations per host
    dispatch.  Semantics match the imperative path: each loss has its own
    scaler, the two D losses accumulate into one Adam step that skips if
    EITHER overflowed, and the G phase sees the UPDATED discriminator."""
    from apex_tpu.amp.loss_scaler import LossScaler

    if opt.opt_level not in ("O0", "O1"):
        raise SystemExit(f"pipelined dcgan supports O0/O1 (the reference "
                         f"example's levels); got {opt.opt_level} — use "
                         f"--imperative for the full opt-level surface")
    if opt.opt_level == "O1":
        amp.init()                      # O1 autocast inside the traced loss

    key = jax.random.PRNGKey(0)
    netG, netD, gv, dv = _build_models(opt, key)
    g_state = {k: v for k, v in gv.items() if k != "params"}
    d_state = {k: v for k, v in dv.items() if k != "params"}
    real_label, fake_label = 1.0, 0.0

    def d_loss_real(d_params, real):
        out, _ = netD.apply({"params": d_params, **d_state}, real,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    def d_loss_fake(d_params, fake):
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, fake_label)

    def g_loss(g_params, d_params, noise):
        fake, _ = netG.apply({"params": g_params, **g_state}, noise,
                             train=True, mutable=["batch_stats"])
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    # Three scalers, one per loss (the num_losses=3 contract), dynamic
    # under amp exactly like amp.initialize's default.
    dynamic = opt.opt_level != "O0"
    scalers = [LossScaler("dynamic" if dynamic else 1.0) for _ in range(3)]
    tx = training.adam(lr=opt.lr, beta1=opt.beta1, beta2=0.999)

    state = {
        "g": gv["params"], "d": dv["params"],
        "g_opt": tx.init(gv["params"]), "d_opt": tx.init(dv["params"]),
        "s0": scalers[0].init(), "s1": scalers[1].init(),
        "s2": scalers[2].init(),
    }

    def step_fn(state, batch):
        real, noise = batch
        # (1) D phase: G forward (detached) + BOTH D backwards, each loss
        # scaled by its own scaler; the two unscaled grads accumulate
        # into ONE Adam step that skips when EITHER loss overflowed
        # (apex semantics: backward-accumulate then step-or-skip).
        fake, _ = netG.apply({"params": state["g"], **g_state}, batch[1],
                             train=True, mutable=["batch_stats"])
        fake = jax.lax.stop_gradient(fake)
        errR, gR = jax.value_and_grad(
            lambda p: jnp.float32(d_loss_real(p, real))
            * state["s0"].loss_scale)(state["d"])
        errF, gF = jax.value_and_grad(
            lambda p: jnp.float32(d_loss_fake(p, fake))
            * state["s1"].loss_scale)(state["d"])
        gR, s0 = scalers[0].unscale(gR, state["s0"])
        gF, s1 = scalers[1].unscale(gF, state["s1"])
        mask_d = (jnp.logical_not(s0.overflow | s1.overflow)
                  if dynamic else None)
        g_d = jax.tree_util.tree_map(lambda a, b: a + b, gR, gF)
        d_new, d_opt = tx.update(g_d, state["d_opt"], state["d"],
                                 apply_mask=mask_d)
        # (2) G phase, loss_id=2, against the UPDATED discriminator —
        # same ordering as the imperative loop (optimizerD.step() runs
        # before g_phase reads optimizerD.params).
        errG, gG = jax.value_and_grad(
            lambda p: jnp.float32(g_loss(p, d_new, noise))
            * state["s2"].loss_scale)(state["g"])
        gG, s2 = scalers[2].unscale(gG, state["s2"])
        mask_g = jnp.logical_not(s2.overflow) if dynamic else None
        g_new, g_opt = tx.update(gG, state["g_opt"], state["g"],
                                 apply_mask=mask_g)
        metrics = {
            # unscaled for display (err* carry their loss's scale)
            "loss_d": (errR / state["s0"].loss_scale
                       + errF / state["s1"].loss_scale),
            "loss_g": errG / state["s2"].loss_scale,
            "scale": state["s2"].loss_scale,
        }
        new_state = {
            "g": g_new, "d": d_new, "g_opt": g_opt, "d_opt": d_opt,
            "s0": scalers[0].update_scale(s0),
            "s1": scalers[1].update_scale(s1),
            "s2": scalers[2].update_scale(s2),
        }
        return new_state, metrics

    # Elastic checkpoint/resume + preemption drain (ISSUE 9): the whole
    # functional carry — both parameter trees, both Adam states, all
    # three loss-scale machines — is one pytree, so the manager
    # checkpoints GAN training with the same code path as the others.
    mgr = None
    start_step = 0
    if opt.checkpoint_dir:
        from apex_tpu import checkpoint as apex_checkpoint
        mgr = apex_checkpoint.CheckpointManager(
            opt.checkpoint_dir,
            every_steps=max(1, opt.checkpoint_every))
        if opt.resume:
            restored = mgr.restore(like=state)
            if restored is not None:
                state = restored.state
                start_step = restored.step
                from apex_tpu import telemetry
                rec = telemetry.get_recorder()
                if rec is not None:
                    rec.run_id = mgr.run_id
                    rec.event("resume", run_id=mgr.run_id,
                              step=start_step)
                print(f"resumed at iter {start_step} "
                      f"(run {mgr.run_id}) from {opt.checkpoint_dir}")
    stop = runtime.GracefulShutdown().install() if opt.drain else None

    spc = max(1, opt.steps_per_call)
    total = opt.niter * opt.iters_per_epoch
    # Reused pool window: spc distinct pool batches stacked once — must
    # NOT be donated (streamed real data would stage fresh windows via
    # runtime.stage_windows and donate them).
    pool = _synthetic_pool(opt)
    window = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *(pool[i % len(pool)] for i in range(spc)))
    pipe = runtime.StepPipeline(step_fn, spc, donate_window=False)

    print_every = max(1, -(-opt.print_freq // spc)) \
        if opt.print_freq > 0 else 0       # cadence in WINDOWS

    t0 = time.perf_counter()
    t_steady = None
    warm_iters = 0
    reader = runtime.DeferredMetrics()
    ipe = opt.iters_per_epoch

    def emit(wm):
        """One window's loss lines from ONE stacked device->host
        transfer, one dispatch behind the loop."""
        vals = wm.fetch()
        last = wm.n_valid - 1
        it_done = wm.step + wm.n_valid
        print(f"[{(it_done - 1) // ipe}/{opt.niter}]"
              f"[{(it_done - 1) % ipe}/{ipe}] "
              f"Loss_D: {np.ravel(vals['loss_d'])[last]:.4f} "
              f"Loss_G: {np.ravel(vals['loss_g'])[last]:.4f}")

    ci = 0
    while start_step + reader.steps_pushed < total:
        n_valid = min(spc, total - start_step - reader.steps_pushed)
        state, metrics = pipe.step_window(state, window, n_valid)
        prev = reader.push(metrics, n_valid)
        if ci <= 1:
            # Calls 0 AND 1 both compile (call 1 re-specializes on the
            # committed output shardings); drain them synchronously so
            # the steady clock starts after both.
            reader.newest().fetch()
            t_steady = time.perf_counter()
            warm_iters = reader.steps_pushed
        if prev is not None and print_every \
                and (prev.step // spc) % print_every == 0:
            emit(prev)
        ci += 1
        gstep = start_step + reader.steps_pushed
        if stop is not None and stop.draining:
            if mgr is not None:
                mgr.save(gstep, state, block=True)
            print(f"drain: stopping at iter {gstep} ({stop.reason})")
            break
        if mgr is not None:
            mgr.maybe_save(gstep, state)
    if reader.newest() is not None:
        emit(reader.newest())             # doubles as the pipeline drain
    if mgr is not None:
        gstep = start_step + reader.steps_pushed
        if mgr.last_saved != gstep:
            mgr.save(gstep, state, block=True)
        mgr.close()
        print(f"checkpoint: iter {gstep} saved under "
              f"{opt.checkpoint_dir}")
    if stop is not None:
        stop.uninstall()
    t1 = time.perf_counter()
    # ACTUAL iterations dispatched (a drain break stops early — dividing
    # the planned total by the short wall would inflate the it/s lines
    # bench.py parses)
    n_done = reader.steps_pushed
    n_steady = n_done - warm_iters
    if t_steady is not None and n_steady > 0:
        print(f"steady {n_steady / (t1 - t_steady):.2f} it/s over "
              f"{n_steady} iters (excl first 2 calls)")

    # Best-of-3 windows under the repo's min-of-reps timing policy: one
    # steady window can eat a multi-second tunnel stall; each timed
    # window is 2 calls (2*spc iters) fenced by one stacked metric fetch.
    if total >= spc and spc > 1:
        best = float("inf")
        for _ in range(3):
            tw = time.perf_counter()
            for _ in range(2):
                state, metrics = pipe.step_window(state, window, spc)
            runtime.WindowMetrics(0, spc, metrics).fetch()
            best = min(best, (time.perf_counter() - tw) / (2 * spc))
        print(f"best-of-3 windows: {1.0 / best:.2f} it/s "
              f"({best * 1e3:.1f} ms/iter over {2 * spc}-iter windows)")
    # Parsed by bench.py into loader_stall_pct: the pool is fully
    # pre-staged, so by construction the loop never waits on input.
    print("loader: stall 0.00% (pre-staged synthetic pool)")
    # HBM memory ledger (ISSUE 10): emits the `memory` event + the
    # peak_hbm_bytes gauge the exit health: line reads.
    try:
        mem = pipe.memory_stats()
        if mem is not None:
            print(f"memory: peak-hbm {mem['peak_bytes'] / 1e6:.1f}MB")
    except Exception as e:                       # pragma: no cover
        print(f"memory: ledger unavailable ({type(e).__name__}: {e})")
    print(f"done in {t1 - t0:.1f}s ({n_done / (t1 - t0):.2f} it/s)")


# -- imperative mode: the reference-parity amp surface ------------------------

def main_imperative(opt):
    key = jax.random.PRNGKey(0)
    netG, netD, gv, dv = _build_models(opt, key)

    optimizerG = FusedAdam(gv["params"], lr=opt.lr, betas=(opt.beta1, 0.999))
    optimizerD = FusedAdam(dv["params"], lr=opt.lr, betas=(opt.beta1, 0.999))

    # Multi-model / multi-optimizer / multi-loss init (reference
    # main_amp.py:214-215).
    [gp, dp], [optimizerG, optimizerD] = amp.initialize(
        [optimizerG.params, optimizerD.params], [optimizerG, optimizerD],
        opt_level=opt.opt_level, num_losses=3)

    g_state = {k: v for k, v in gv.items() if k != "params"}
    d_state = {k: v for k, v in dv.items() if k != "params"}
    real_label, fake_label = 1.0, 0.0

    def d_loss_real(d_params, real):
        out, _ = netD.apply({"params": d_params, **d_state}, real,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    def d_loss_fake(d_params, fake):
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, fake_label)

    def g_loss(g_params, d_params, noise):
        fake, _ = netG.apply({"params": g_params, **g_state}, noise,
                             train=True, mutable=["batch_stats"])
        out, _ = netD.apply({"params": d_params, **d_state}, fake,
                            train=True, mutable=["batch_stats"])
        return bce_with_logits(out, real_label)

    # TWO jitted programs per iteration phase pair (r5, VERDICT r4 next
    # #6): the whole D phase — G forward (detached) + BOTH D backwards —
    # is ONE compiled program instead of three; each dispatch through a
    # tunneled chip costs ~7 ms fixed + ~22 us/leaf-arg, so programs are
    # the unit of cost here.  Params AND loss scales enter as jit
    # ARGUMENTS (live values each call): closing over optimizer.params
    # inside an outer jit would freeze the weights at trace time — the
    # exact bug this file shipped with for four rounds.
    from apex_tpu.amp._amp_state import _amp_state

    def live_scale(i):
        return _amp_state.loss_scalers[i].state.loss_scale

    @jax.jit
    def d_phase(d_params, g_params, real, noise, s0, s1):
        fake, _ = netG.apply({"params": g_params, **g_state}, noise,
                             train=True, mutable=["batch_stats"])
        fake = jax.lax.stop_gradient(fake)
        err_r, g_r = jax.value_and_grad(
            lambda p: jnp.float32(d_loss_real(p, real)) * s0)(d_params)
        err_f, g_f = jax.value_and_grad(
            lambda p: jnp.float32(d_loss_fake(p, fake)) * s1)(d_params)
        return err_r, g_r, err_f, g_f

    @jax.jit
    def g_phase(g_params, d_params, noise, s2):
        return jax.value_and_grad(
            lambda p: jnp.float32(g_loss(p, d_params, noise)) * s2)(
                g_params)

    pool = _synthetic_pool(opt)

    def train_iter(idx):
        """One imperative iteration — shared by the main loop AND the
        best-of-3 timing windows so both measure the same computation.
        Returns the (scaled) losses and the scales used."""
        real, noise = pool[idx % len(pool)]
        # (1) D phase: ONE program — G fwd (detached) + D-real + D-fake
        # backwards; separate scalers per loss (loss_id=0/1).
        s0, s1 = live_scale(0), live_scale(1)
        errD_real, gD, errD_fake, gDf = d_phase(
            optimizerD.params, optimizerG.params, real, noise, s0, s1)
        with amp.scale_loss(errD_real, optimizerD, loss_id=0):
            optimizerD.backward(gD)
        with amp.scale_loss(errD_fake, optimizerD, loss_id=1):
            optimizerD.backward(gDf)
        optimizerD.step()
        # (2) G, loss_id=2 (grads w.r.t. G through D)
        s2 = live_scale(2)
        errG, gG = g_phase(optimizerG.params, optimizerD.params, noise, s2)
        with amp.scale_loss(errG, optimizerG, loss_id=2):
            optimizerG.backward(gG)
        optimizerG.step()
        return errD_real, errD_fake, errG, s0, s1, s2

    def drain():
        """Force the pipeline: one scalar fetch of the LAST update's
        output (block_until_ready is a no-op through the tunnel)."""
        float(jnp.ravel(jax.tree_util.tree_leaves(
            optimizerG.params)[-1])[0].astype(jnp.float32))

    t0 = time.perf_counter()
    total = opt.niter * opt.iters_per_epoch
    t_steady = t0 if opt.warmup <= 0 else None
    it = 0
    for epoch in range(opt.niter):
        for i in range(opt.iters_per_epoch):
            errD_real, errD_fake, errG, s0, s1, s2 = train_iter(it)
            it += 1
            if it == opt.warmup and it < total:
                # Warm the print path too before starting the steady
                # clock: the division/stack pack compiles on first use,
                # which is SECONDS through a tunneled chip and would
                # otherwise land inside the steady window at the first
                # print (measured: 3.45 -> ~30 it/s steady).
                # jaxlint: disable=J001 -- deliberate one-off warmup fetch: compiles the print path before the steady clock starts
                np.asarray(jnp.stack([errD_real / s0, errD_fake / s1,
                                      errG / s2]))
                t_steady = time.perf_counter()     # compiles are behind us
            if (opt.print_freq > 0 and it % opt.print_freq == 0) \
                    or it == total:
                # ONE stacked device->host transfer per print (each
                # separate float() is a full pipeline-drain round-trip
                # through the tunnel); losses are unscaled for display.
                # jaxlint: disable=J001 -- print-frequency-gated: one stacked transfer per print window, not per step
                packed = np.asarray(jnp.stack([
                    errD_real / s0, errD_fake / s1, errG / s2]))
                print(f"[{epoch}/{opt.niter}][{i}/{opt.iters_per_epoch}] "
                      f"Loss_D: {packed[0] + packed[1]:.4f} "
                      f"Loss_G: {packed[2]:.4f}")
    drain()
    t1 = time.perf_counter()
    if t_steady is not None and total > opt.warmup:
        n_steady = total - opt.warmup
        print(f"steady {n_steady / (t1 - t_steady):.2f} it/s over "
              f"{n_steady} iters (excl {opt.warmup} warmup)")

    # Best-of-3 windows under the repo's min-of-reps timing policy: the
    # single steady window above can eat a multi-second tunnel stall
    # (the same loop measured 23 ms and 200 ms per iter in back-to-back
    # windows; device trace shows ~2 ms/iter of actual device work), so
    # the rate the loop DEMONSTRABLY achieves is reported beside it.
    if total >= 8:         # skipped in tiny CPU smokes
        k = 8
        best = float("inf")
        for _ in range(3):
            drain()
            tp_ = time.perf_counter()
            for j in range(k):
                train_iter(it + j)
            drain()
            best = min(best, (time.perf_counter() - tp_) / k)
            it += k
        print(f"best-of-3 windows: {1.0 / best:.2f} it/s "
              f"({best * 1e3:.1f} ms/iter over {k}-iter windows)")

    # Dispatch budget (VERDICT r4 next #6): the imperative path's floor on
    # a tunneled chip is per-program fixed cost + per-leaf-arg cost; print
    # the computed floor next to the measured rate so the gap between
    # "tunnel physics" and "program structure" is a number, not a vibe.
    # INPUT leaf-args only (outputs ride the same transfers; the ~22 us
    # constant was measured per input leaf): d_phase takes D+G params +
    # 2 batches + 2 scales; g_phase takes G+D params + noise + scale;
    # each step() program takes grads + adam (m, v) + params = 4 trees.
    n_d = len(jax.tree_util.tree_leaves(optimizerD.params))
    n_g = len(jax.tree_util.tree_leaves(optimizerG.params))
    n_leaves = ((n_d + n_g + 4)          # d_phase
                + (n_g + n_d + 2)        # g_phase
                + 4 * n_d + 4 * n_g)     # stepD + stepG
    # Also dispatched per iter: 6 TINY jitted scaler programs (3 jitted
    # unscale/axpby sweeps + 3 update_scale lanes — r5 moved these from
    # ~100 eager per-leaf dispatches, which cost ~0.8 ms EACH through
    # the tunnel and dominated the loop at 261 ms/iter).  Their measured
    # contribution is small (best window ~33 ms/iter lands ON the
    # 4-heavy-program floor), so the floor counts the heavy programs
    # only and names what it excludes.
    floor_ms = 4 * 7.0 + n_leaves * 0.022
    print(f"dispatch budget: 4 heavy + 6 tiny jitted programs/iter, "
          f"~{n_leaves} leaf-args/iter, "
          f"floor ~{floor_ms:.1f} ms/iter "
          f"({1000.0 / floor_ms:.1f} it/s tunnel-physics bound)")
    print("loader: stall 0.00% (pre-staged synthetic pool)")
    print(f"done in {t1 - t0:.1f}s ({total / (t1 - t0):.2f} it/s)")


def main():
    opt = parse()
    if opt.imperative and (opt.checkpoint_dir or opt.resume):
        raise SystemExit(
            "--checkpoint-dir/--resume need the pipelined default (the "
            "functional state carry is what the manager snapshots); "
            "drop --imperative")
    rec = None
    use_watchdog = (opt.watchdog if opt.watchdog is not None
                    else bool(opt.telemetry))
    if (opt.telemetry or use_watchdog or opt.metrics_port is not None
            or opt.metrics_textfile):
        # Active recorder installed before either mode builds its loop:
        # the pipelined path records window/gap/metrics events through
        # StepPipeline; the imperative path records the per-step
        # optimizer spans and deferred-overflow skip events.  The
        # watchdog (default-on under --telemetry) folds them online.
        from apex_tpu import telemetry
        rec = telemetry.start(
            opt.telemetry or _os.devnull, watchdog=use_watchdog,
            example="dcgan",
            export_port=opt.metrics_port,
            export_textfile=opt.metrics_textfile,
            mode="imperative" if opt.imperative else "pipelined",
            opt_level=opt.opt_level, steps_per_call=opt.steps_per_call)
        if rec.exporter is not None:
            print(f"metrics export: {rec.exporter.describe()}")
    try:
        if opt.imperative:
            main_imperative(opt)
        else:
            main_pipelined(opt)
    finally:
        if rec is not None:
            wd = rec.watchdog
            rec.close()
            if opt.telemetry:
                print(f"telemetry: {opt.telemetry} "
                      f"(python -m apex_tpu.prof.timeline to analyze)")
            if wd is not None:
                extras = ""
                peak = rec.metrics.gauge("peak_hbm_bytes").value
                if peak:
                    extras += f"  peak-hbm {peak / 1e6:.1f}MB"
                if rec.exporter is not None:
                    extras += f"  export {rec.exporter.describe()}"
                print(f"health: {wd.format_line()}{extras}")


if __name__ == "__main__":
    main()
