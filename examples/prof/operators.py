"""prof example 8 — operator sweep inside a profiling window.

The analog of reference ``apex/pyprof/examples/operators.py`` +
``simple.py``: exercise the elementary tensor operators (unary/binary
dunders, comparisons, matmul) and show the START/STOP window semantics —
only work issued inside ``prof.trace`` is captured, the TPU mirror of
``--profile-from-start off`` + ``profiler.start()/stop()``.

    python examples/prof/operators.py [logdir]
"""

import sys
import tempfile

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof

UNARY = ["__abs__", "__neg__"]
BINARY = ["__add__", "__sub__", "__mul__", "__truediv__", "__pow__",
          "__matmul__"]
COMPARE = ["__lt__", "__le__", "__eq__", "__ne__", "__ge__", "__gt__"]
INT_BINARY = ["__and__", "__or__", "__xor__", "__lshift__", "__rshift__",
              "__mod__", "__floordiv__"]


@prof.annotate("operator_sweep")
def sweep(fa, fb, ia, ib):
    outs = []
    for op in UNARY:
        outs.append(getattr(fa, op)())
    for op in BINARY:
        outs.append(getattr(fa, op)(fb))
    for op in COMPARE:
        outs.append(getattr(fa, op)(fb).astype(jnp.float32))
    for op in INT_BINARY:
        outs.append(getattr(ia, op)(ib).astype(jnp.float32))
    return sum(jnp.sum(o.astype(jnp.float32)) for o in outs)


def main():
    logdir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="apex_tpu_prof_ops_")
    prof.init()
    rng = np.random.RandomState(0)
    fa = jnp.asarray(rng.rand(256, 256) + 0.5, jnp.float32)
    fb = jnp.asarray(rng.rand(256, 256) + 0.5, jnp.float32)
    ia = jnp.asarray(rng.randint(1, 100, (256, 256)), jnp.int32)
    ib = jnp.asarray(rng.randint(1, 8, (256, 256)), jnp.int32)

    fn = jax.jit(sweep)
    # OUTSIDE the window: compile + warm-up are not profiled.
    float(fn(fa, fb, ia, ib))

    with prof.trace(logdir):                  # profiler.start()
        total = float(fn(fa, fb, ia, ib))
    # profiler.stop() — work after this point is not captured.
    float(fn(fa, fb, ia, ib))
    print(f"operator sweep total {total:.3e}; trace in {logdir}")

    p = prof.profile_function(sweep, fa, fb, ia, ib)
    print(p.summary(top=12))
    n_ops = len(UNARY) + len(BINARY) + len(COMPARE) + len(INT_BINARY)
    print(f"swept {n_ops} operators")


if __name__ == "__main__":
    main()
