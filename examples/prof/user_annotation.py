"""prof example 2 — user annotations.

The analog of reference ``apex/pyprof/examples/user_annotation/``: custom
scope names around semantically meaningful blocks (the resnet
"layer:4, block:7" pattern) so the profile groups ops the way the model
author thinks about them.

    python examples/prof/user_annotation.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof

prof.init()                                  # enable arg markers


@prof.annotate("bottleneck_block")
def bottleneck(x, w1, w2):
    with prof.scope("pointwise_in"):
        h = x @ w1
    with prof.scope("activation"):
        h = jax.nn.relu(h)
    with prof.scope("pointwise_out"):
        return h @ w2 + x


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(64, 256), jnp.float32)
    w1 = jnp.asarray(rng.rand(256, 64), jnp.float32)
    w2 = jnp.asarray(rng.rand(64, 256), jnp.float32)

    # Markers record op name + arg shapes/dtypes per call (the reference's
    # traceMarker/argMarker dicts).
    y = bottleneck(x, w1, w2)
    print("markers recorded:", len(prof.MARKERS))
    print(prof.MARKERS[-1]["op"], prof.MARKERS[-1]["args"][0])

    # The scope names appear in the static per-op records too.
    profile = prof.profile_function(bottleneck, x, w1, w2)
    for r in profile.records[:10]:
        if r.name:
            print(f"{r.name:<40} {r.op:<16} {r.flops:>12.0f} flops")
    jax.block_until_ready(y)


if __name__ == "__main__":
    main()
