"""prof example 1 — lenet-style model walk-through.

The analog of reference ``apex/pyprof/examples/lenet.py``: instrument a
small convnet, run the static per-op analysis, print the flops/bytes
report.  Runs on CPU or TPU:

    python examples/prof/lenet.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np

from apex_tpu import prof


class LeNet(nn.Module):
    @nn.compact
    def __call__(self, x):                      # x: [N, 32, 32, 1] NHWC
        with prof.scope("conv1"):
            x = nn.relu(nn.Conv(6, (5, 5))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        with prof.scope("conv2"):
            x = nn.relu(nn.Conv(16, (5, 5))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        with prof.scope("classifier"):
            x = nn.relu(nn.Dense(120)(x))
            x = nn.relu(nn.Dense(84)(x))
            return nn.Dense(10)(x)


def main():
    model = LeNet()
    x = jnp.asarray(np.random.RandomState(0).rand(8, 32, 32, 1), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)

    def fwd(params, x):
        return model.apply(params, x)

    profile = prof.profile_function(fwd, params, x)
    print(profile.summary(top=15))
    print("\ntotal GFLOPs: {:.3f}".format(profile.total_flops / 1e9))


if __name__ == "__main__":
    main()
