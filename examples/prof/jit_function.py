"""prof example 6 — naming jitted functions in profiles.

The analog of reference ``apex/pyprof/examples/jit/`` (jit_script_function
/ jit_script_method / jit_trace_*): a compiled function is opaque to a
profiler unless a name is attached at the right point.  The reference
wraps ``torch.jit`` objects AFTER scripting (``pyprof.nvtx.wrap(foo,
'forward')``); the TPU rule is the mirror image: annotate INSIDE (or
around) the traced function, because ``jax.jit`` compiles the traced
jaxpr and only scopes present at trace time reach the HLO metadata.

    python examples/prof/jit_function.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp

from apex_tpu import prof


# 1. A function annotated BEFORE jit: prof.annotate records call markers
#    (the reference's argMarker dict) and opens a named scope that lands
#    in the compiled HLO's metadata, so both the static analysis and a
#    device trace attribute its ops to "foo".
@prof.annotate("foo")
def foo(x, y):
    return jax.nn.sigmoid(x) + y


foo_jit = jax.jit(foo)


# 2. A method: same decorator on the class method (the reference's
#    jit_script_method recipe).
class Model:
    def __init__(self, w):
        self.w = w

    @prof.annotate("Model.forward")
    def forward(self, x):
        return jnp.tanh(x @ self.w)


# 3. An ALREADY-jitted function someone handed us (the jit_trace_*
#    situation): wrap the call site in a scope — trace-time names can no
#    longer be injected, but the profiler window still brackets it.
def third_party(x):
    return jnp.exp(x) * 2.0


third_party_jit = jax.jit(third_party)


def main():
    prof.init()                     # enable call markers
    x = jnp.zeros((4, 4))
    y = jnp.ones((4, 4))
    m = Model(jnp.ones((4, 8)))

    z = foo_jit(x, y)
    h = m.forward(x)
    with prof.scope("third_party"):
        t = third_party_jit(x)
    print("foo:", z.sum(), " forward:", h.sum(), " third_party:", t.sum())

    # The static analysis shows ops grouped under the annotation scopes.
    p = prof.profile_function(foo, x, y)
    print(p.summary(top=5))
    recorded = [m["op"] for m in prof.MARKERS]
    print("markers recorded:", recorded)
    assert any("foo" in n for n in recorded)
    assert any("Model.forward" in n for n in recorded)


if __name__ == "__main__":
    main()
