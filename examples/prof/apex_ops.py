"""prof example 7 — profiling apex_tpu's own fused components.

The analog of reference ``apex/pyprof/examples/apex/`` (fused_adam.py,
fused_layer_norm.py): point the profiler at the library's own fused ops
and read their cost records — the multi-tensor Adam update over a whole
parameter tree, and FusedLayerNorm forward + backward (the Pallas kernel
on TPU, the jnp fallback elsewhere; both profile identically because the
analysis walks the jaxpr).

    python examples/prof/apex_ops.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.optimizers import functional as F


def main():
    rng = np.random.RandomState(0)

    # -- FusedAdam: the whole-model single-program update ------------------
    params = {f"layer{i}": {"w": jnp.asarray(rng.randn(128, 128) / 11,
                                             jnp.float32),
                            "b": jnp.zeros((128,), jnp.float32)}
              for i in range(8)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-3, p.dtype), params)
    opt_state = F.adam_init(params)

    @prof.annotate("fused_adam_step")
    def adam_step(g, s, p):
        return F.adam_update(g, s, p, lr=1e-3)

    p = prof.profile_function(adam_step, grads, opt_state, params)
    print("== FusedAdam whole-tree update ==")
    print(p.summary(top=8))

    # -- FusedLayerNorm: fwd + bwd -----------------------------------------
    # impl="pallas": this section profiles the KERNEL; [64, 256] is far
    # below the r5 auto-dispatch crossover and would route to jnp.
    ln = FusedLayerNorm(normalized_shape=256, impl="pallas")
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)
    variables = ln.init(jax.random.PRNGKey(0), x)

    def ln_loss(v, x):
        return jnp.sum(ln.apply(v, x).astype(jnp.float32) ** 2)

    grad_fn = jax.grad(ln_loss)
    p = prof.profile_function(grad_fn, variables, x)
    print("== FusedLayerNorm fwd+bwd ==")
    print(p.summary(top=8))

    # Sanity: both really execute.
    out = jax.jit(adam_step)(grads, opt_state, params)
    g = jax.jit(grad_fn)(variables, x)
    print("adam ok:", float(jnp.ravel(
        jax.tree_util.tree_leaves(out[0])[0])[0]),
        " ln grad ok:", float(jnp.ravel(
            jax.tree_util.tree_leaves(g)[0])[0]))


if __name__ == "__main__":
    main()
