"""prof example 4 — full capture → parse → joined report.

The analog of the reference's imagenet pyprof recipe
(``apex/pyprof/examples/imagenet/``): capture a *measured* device trace of
a jitted train step, run the static analysis, and join measured
microseconds onto analytic flops/bytes per op.

    python examples/prof/end_to_end.py [logdir]

The measured stage needs a real device trace; on CPU the trace may contain
host ops only, in which case the report falls back to static columns.
"""

import sys
import tempfile

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof, training
from apex_tpu.models import bert_tiny
from apex_tpu.training import make_train_step


def main():
    logdir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="apex_tpu_prof_")

    model = bert_tiny(dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 1024, (8, 64)))
    labels = jnp.asarray(rng.randint(0, 2, (8,)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, batch):
        ids_b, y = batch
        logits = model.apply({"params": p}, ids_b)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-3),
                                       opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))

    # Warm up (compile outside the trace window), then capture 3 steps.
    state, metrics = step(state, (ids, labels))
    jax.block_until_ready(metrics["loss"])
    with prof.trace(logdir):
        for _ in range(3):
            state, metrics = step(state, (ids, labels))
        jax.block_until_ready(metrics["loss"])
    print("trace written to", logdir)

    profile = prof.profile_function(step_fn, state, (ids, labels))
    try:
        trace = prof.parse_trace(logdir)
        print(prof.attach_measured(profile, trace, top=20))
    except FileNotFoundError:
        print("no device trace found (host-only run); static summary:")
        print(profile.summary(top=20))


if __name__ == "__main__":
    main()
