"""prof example 5 — imagenet-scale model profiling.

The analog of reference ``apex/pyprof/examples/imagenet/imagenet.py``:
profile any model of the ResNet family (forward + backward + the fused
optimizer update, i.e. the whole amp train step) and print the per-op
cost report.  Same CLI shape as the reference (-m model, -b batch,
-o optimizer):

    python examples/prof/imagenet.py -m resnet50 -b 32 -o sgd
    python examples/prof/imagenet.py -m resnet18 -b 8 --image-size 64

On a TPU host the static analysis is joined with a measured device trace;
off-TPU the static (analytic flops/bytes) report prints alone.
"""

import argparse
import tempfile

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof, training
from apex_tpu.models import (ResNet18, ResNet34, ResNet50, ResNet101,
                             ResNet152)
from apex_tpu.training import make_train_step

ARCHS = {"resnet18": ResNet18, "resnet34": ResNet34, "resnet50": ResNet50,
         "resnet101": ResNet101, "resnet152": ResNet152}


def parse():
    p = argparse.ArgumentParser(description="profile imagenet models")
    p.add_argument("-m", default="resnet18", choices=sorted(ARCHS))
    p.add_argument("-b", type=int, default=8)
    p.add_argument("-o", default="sgd", choices=["sgd", "adam"])
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--opt-level", default="O2")
    return p.parse_args()


def main():
    args = parse()
    model = ARCHS[args.m](num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(args.b, args.image_size, args.image_size, 3),
                    jnp.float32)
    y = jnp.asarray(np.arange(args.b) % 1000)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, upd = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, upd["batch_stats"]

    tx = (training.sgd(0.1, momentum=0.9) if args.o == "sgd"
          else training.adam(1e-3))
    init_fn, step_fn = make_train_step(loss_fn, tx,
                                       opt_level=args.opt_level,
                                       has_model_state=True)
    state = init_fn(variables["params"], variables["batch_stats"])

    # Static per-op analysis of the WHOLE train step (fwd + bwd + update).
    profile = prof.profile_function(step_fn, state, (x, y))
    print(f"== {args.m} b{args.b} {args.opt_level} {args.o}: static ==")
    print(profile.summary(top=15))

    # Measured pass: capture 3 real steps, join device microseconds.
    step = jax.jit(step_fn, donate_argnums=(0,))
    state, metrics = step(state, (x, y))          # compile outside trace
    float(jnp.ravel(metrics["loss"])[0])
    logdir = tempfile.mkdtemp(prefix="apex_tpu_prof_imagenet_")
    with prof.trace(logdir):
        for _ in range(3):
            state, metrics = step(state, (x, y))
        float(jnp.ravel(metrics["loss"])[0])
    try:
        tracep = prof.parse_trace(logdir)
        print("== measured (device trace) ==")
        print(prof.attach_measured(profile, tracep, top=15))
    except (FileNotFoundError, ValueError):
        print("no device trace (host-only run); static report above is "
              "the result")


if __name__ == "__main__":
    main()
