"""prof example 3 — profiling custom ops.

The analog of reference ``apex/pyprof/examples/custom_func_module/``:
a user-defined op (custom VJP) is annotated so both its forward and its
custom backward show up under recognizable names in the profile.

    python examples/prof/custom_func_module.py
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import prof


@jax.custom_vjp
def swishish(x, beta):
    return x * jax.nn.sigmoid(beta * x)


def _fwd(x, beta):
    with jax.named_scope("swishish_fwd"):
        s = jax.nn.sigmoid(beta * x)
        return x * s, (x, s, beta)


def _bwd(res, g):
    x, s, beta = res
    with jax.named_scope("swishish_bwd"):
        ds = s * (1 - s)
        dx = g * (s + x * beta * ds)
        dbeta = jnp.sum(g * x * x * ds)
        return dx, dbeta


swishish.defvjp(_fwd, _bwd)


def main():
    x = jnp.asarray(np.random.RandomState(0).rand(512, 512), jnp.float32)
    beta = jnp.float32(1.5)

    def loss(x, beta):
        return jnp.sum(swishish(x, beta))

    profile = prof.profile_function(jax.grad(loss, argnums=(0, 1)), x, beta)
    print(profile.summary(top=12))
    bwd_records = [r for r in profile.records if "swishish_bwd" in r.name]
    print(f"\ncustom-backward ops profiled: {len(bwd_records)}")


if __name__ == "__main__":
    main()
