"""Causal-LM pretraining with amp — the long-context training example.

No reference counterpart (apex ships no LM example); this is the
framework's long-context showcase: GPT with Pallas flash attention, the
fused label-smoothing xentropy loss, FusedAdam with the BERT-style
no-decay-on-bias/LayerNorm parameter groups, and the fully-jitted amp
train step.  With ``--sp N`` the sequence is sharded over an ``sp`` mesh
axis and attention runs as ring attention (``--attention ring`` or
``ring_flash``).

The loop runs on :class:`apex_tpu.runtime.StepPipeline`:
``--steps-per-call K`` chains K steps into ONE compiled program
(BENCH r05: BERT runs 14.8 ms/step in a device loop vs 24.2 ms wall
jitted-per-step — pure dispatch), and the per-step loss lines print one
dispatch behind from the window's stacked metrics, so the hot loop
never blocks on a scalar.

    python main_amp.py --synthetic --steps 5 --seq-len 256 --opt-level O2
    python main_amp.py --synthetic --steps 32 --steps-per-call 8
    python main_amp.py --synthetic --steps 2 --sp 2 --attention ring
"""

import os as _os
import sys as _sys

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), *[_os.pardir] * 2)))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import runtime, training
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models import GPT
from apex_tpu.training import make_train_step


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--opt-level", type=str, default="O2")
    p.add_argument("--loss-scale", type=str, default=None)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--weight-decay", type=float, default=0.1)
    p.add_argument("--smoothing", type=float, default=0.0)
    p.add_argument("--fused-loss", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="contrib.xentropy fused softmax-cross-entropy on "
                        "the vocab-sized logits (the textbook case: one "
                        "pass, saves only max_log_sum_exp instead of "
                        "materialized log-probs).  --no-fused-loss keeps "
                        "the log_softmax+gather reference composition — "
                        "the smoke test asserts loss parity between the "
                        "two (ISSUE 7)")
    p.add_argument("--attention", type=str, default="flash",
                   choices=["full", "blockwise", "flash", "ring",
                            "ring_flash", "ulysses"])
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel ways (needs >= sp devices)")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="GQA/MQA: kv heads shared across query heads "
                        "(must divide --heads; flash kernel shares KV "
                        "via index maps)")
    p.add_argument("--window", type=int, default=None,
                   help="sliding-window local attention (causal, "
                        "O(T*window) on the flash kernel)")
    p.add_argument("--steps-per-call", type=int, default=1,
                   help="chain N train steps into ONE compiled program "
                        "(apex_tpu.runtime.StepPipeline); host dispatch "
                        "and the metric fetch then cost once per N steps "
                        "— loss lines print one dispatch behind")
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   metavar="DIR",
                   help="async sharded checkpointing "
                        "(apex_tpu.checkpoint.CheckpointManager) every "
                        "--checkpoint-every steps at window boundaries")
    p.add_argument("--checkpoint-every", type=int, default=100,
                   help="save cadence in steps (window-boundary floored)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint under "
                        "--checkpoint-dir: params/optimizer/scaler "
                        "state, step counter, and telemetry run-id "
                        "round-trip bit-identically")
    p.add_argument("--drain", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="graceful SIGTERM/SIGINT drain (ON by default): "
                        "finish the window, write a final checkpoint, "
                        "flush the recorder; second signal hard-stops")
    p.add_argument("--telemetry", type=str, default=_os.environ.get(
                       "APEX_TPU_TELEMETRY") or None, metavar="PATH",
                   help="record the run-telemetry event stream (JSONL) "
                        "to PATH; analyze offline with "
                        "python -m apex_tpu.prof.timeline PATH.  "
                        "Defaults from APEX_TPU_TELEMETRY")
    p.add_argument("--metrics-port", type=int, metavar="PORT",
                   default=(int(_os.environ["APEX_TPU_METRICS_PORT"])
                            if _os.environ.get("APEX_TPU_METRICS_PORT")
                            else None),
                   help="serve live Prometheus metrics on "
                        "http://:PORT/metrics (0 = ephemeral; defaults "
                        "from APEX_TPU_METRICS_PORT)")
    p.add_argument("--metrics-textfile", metavar="PATH",
                   default=_os.environ.get("APEX_TPU_METRICS_TEXTFILE")
                   or None,
                   help="atomically-replaced Prometheus textfile for "
                        "node-exporter scraping (defaults from "
                        "APEX_TPU_METRICS_TEXTFILE)")
    p.add_argument("--watchdog", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="run-health rule engine over the telemetry "
                        "events (debounced alerts + a health: line at "
                        "exit); ON by default when --telemetry is set, "
                        "--no-watchdog disables")
    return p.parse_args()


def main():
    args = parse()
    if not args.synthetic:
        raise SystemExit("only --synthetic data is implemented; pass "
                         "--synthetic (a real-data loader would plug in "
                         "here via apex_tpu.data)")
    rec = None
    use_watchdog = (args.watchdog if args.watchdog is not None
                    else bool(args.telemetry))
    if (args.telemetry or use_watchdog or args.metrics_port is not None
            or args.metrics_textfile):
        # Install the active recorder before the pipeline is built so
        # StepPipeline and the deferred metric reads pick it up.
        from apex_tpu import telemetry
        rec = telemetry.start(args.telemetry or _os.devnull,
                              watchdog=use_watchdog, example="lm",
                              export_port=args.metrics_port,
                              export_textfile=args.metrics_textfile,
                              opt_level=args.opt_level,
                              attention=args.attention,
                              steps_per_call=args.steps_per_call)
        if rec.exporter is not None:
            print(f"metrics export: {rec.exporter.describe()}")
    try:
        # close() in finally: a diverged/killed run still flushes its
        # stream, the summary event, and the watchdog's final alerts.
        _train(args)
    finally:
        if rec is not None:
            wd = rec.watchdog
            rec.close()
            if args.telemetry:
                print(f"telemetry: {args.telemetry} "
                      f"(python -m apex_tpu.prof.timeline to analyze)")
            if wd is not None:
                extras = ""
                peak = rec.metrics.gauge("peak_hbm_bytes").value
                if peak:
                    extras += f"  peak-hbm {peak / 1e6:.1f}MB"
                if rec.exporter is not None:
                    extras += f"  export {rec.exporter.describe()}"
                print(f"health: {wd.format_line()}{extras}")


def _train(args):
    loss_scale = args.loss_scale
    if loss_scale not in (None, "dynamic"):
        loss_scale = float(loss_scale)

    sp = args.sp
    if sp > 1 and args.attention not in ("ring", "ring_flash", "ulysses"):
        raise SystemExit(
            f"--sp {sp} shards the sequence; attention_impl="
            f"{args.attention!r} is shard-local and would silently attend "
            f"within shards only — use ring, ring_flash or ulysses")
    if args.window is not None and args.attention not in ("flash",):
        raise SystemExit("--window needs --attention flash")
    if args.kv_heads is not None and args.attention not in (
            "flash", "blockwise", "full"):
        raise SystemExit("--kv-heads needs --attention flash/blockwise/full "
                         "(GQA is shard-local; ring/ulysses paths are MHA)")
    model = GPT(vocab_size=args.vocab, hidden_size=args.hidden,
                num_layers=args.layers, num_heads=args.heads,
                mlp_dim=4 * args.hidden, max_len=args.seq_len,
                dtype=jnp.bfloat16, attention_impl=args.attention,
                num_kv_heads=args.kv_heads, window=args.window,
                sp_axis="sp" if sp > 1 else None)
    # Same architecture without the sp axis for (replicated) init.
    init_model = model if sp == 1 else model.clone(attention_impl="full",
                                                   sp_axis=None)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, args.vocab,
                                  (args.batch_size, args.seq_len)))
    # Next-token pairs are built GLOBALLY (before any sequence sharding,
    # so labels never cross shard boundaries); T' = seq_len - 1 tokens.
    x_tok, y_tok = ids[:, :-1], ids[:, 1:]
    t_train = args.seq_len - 1
    if sp > 1 and t_train % sp:
        raise SystemExit(f"--seq-len must be 1 + multiple of --sp "
                         f"(got {args.seq_len}, sp={sp})")
    params = init_model.init(jax.random.PRNGKey(0), ids[:1, :8])["params"]
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(params))
    print(f"GPT {args.layers}L/{args.hidden}H  {n_params/1e6:.1f}M params  "
          f"attention={args.attention}  opt_level = {args.opt_level}")

    def loss_fn(p, batch):
        xb, yb = batch
        logits = model.apply({"params": p}, xb)
        flat = logits.reshape(-1, logits.shape[-1])
        labels = yb.reshape(-1)
        if args.fused_loss:
            losses = softmax_cross_entropy_loss(
                flat, labels, smoothing=args.smoothing)
        else:
            # Reference composition (materialized log-probs): the parity
            # oracle the smoke test pins the fused kernel against.  Same
            # padding contract as the fused default (padding_idx=0 —
            # synthetic ids are drawn from [1, vocab), so no row pads).
            logp = jax.nn.log_softmax(flat.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
            smooth = -jnp.mean(logp, axis=-1)
            losses = ((1.0 - args.smoothing) * nll
                      + args.smoothing * smooth)
            losses = jnp.where(labels == 0, 0.0, losses)
        return jnp.mean(losses)

    init_fn, step_fn = make_train_step(
        loss_fn, training.adam(args.lr, weight_decay=args.weight_decay),
        opt_level=args.opt_level, loss_scale=loss_scale,
        axis_name="sp" if sp > 1 else None)
    state = init_fn(params)

    spc = max(1, args.steps_per_call)
    wrap = None
    if sp > 1:
        from apex_tpu.parallel import import_shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        shard_map = import_shard_map()
        devs = jax.devices()[:sp]
        mesh = Mesh(np.array(devs), ("sp",))
        # Sequence sharded over sp; params/batch-rows replicated.  The
        # window's leading K (step) axis stays unsharded; the tail mask
        # is replicated.
        wrap = lambda fn: shard_map(  # noqa: E731
            fn, mesh=mesh,
            in_specs=(P(), (P(None, None, "sp"), P(None, None, "sp")),
                      P()),
            out_specs=(P(), P()))

    # Synthetic data is ONE batch reused every step: pre-stack it into a
    # single [spc, B, T'] window and cycle it device-side — a reused pool
    # window must NOT be donated (streamed real data would stage fresh
    # windows through runtime.stage_windows and donate them).
    pipe = runtime.StepPipeline(step_fn, spc, wrap=wrap,
                                donate_window=False)
    window = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (spc,) + a.shape),
        (x_tok, y_tok))

    tok_per_step = args.batch_size * (args.seq_len - 1)
    tic = time.time()

    def emit(wm):
        """Print one loss line per REAL step of the window, from ONE
        stacked device->host transfer one dispatch behind the loop (the
        per-step float() reads this example used to do were each a full
        pipeline-drain round-trip through a tunneled chip)."""
        nonlocal tic
        vals = wm.fetch()
        toc = time.time()
        tok_s = wm.n_valid * tok_per_step / max(toc - tic, 1e-9)
        loss_k = np.ravel(vals["loss"])
        scale_k = np.ravel(vals["loss_scale"])
        for j in range(wm.n_valid):
            print(f"step {wm.step + j}  loss {loss_k[j]:.4f}  "
                  f"loss_scale {scale_k[j]:.0f}  "
                  f"{tok_s:,.0f} tok/s")
        tic = toc
        return loss_k[wm.n_valid - 1]

    # Elastic checkpoint/resume + preemption drain (ISSUE 9).
    mgr = None
    start_step = 0
    if args.checkpoint_dir:
        from apex_tpu import checkpoint as apex_checkpoint
        mgr = apex_checkpoint.CheckpointManager(
            args.checkpoint_dir,
            every_steps=max(1, args.checkpoint_every))
        if args.resume:
            restored = mgr.restore(like=state)
            if restored is not None:
                state = restored.state
                start_step = restored.step
                from apex_tpu import telemetry as _tel
                rec = _tel.get_recorder()
                if rec is not None:
                    rec.run_id = mgr.run_id
                    rec.event("resume", run_id=mgr.run_id,
                              step=start_step)
                print(f"resumed at step {start_step} "
                      f"(run {mgr.run_id}) from {args.checkpoint_dir}")
    stop = runtime.GracefulShutdown().install() if args.drain else None

    loss = np.float32(np.nan)
    reader = runtime.DeferredMetrics()
    done = start_step
    while done < args.steps:
        n_valid = min(spc, args.steps - done)
        state, metrics = pipe.step_window(state, window, n_valid)
        done += n_valid
        prev = reader.push(metrics, n_valid)
        if prev is not None:
            loss = emit(prev)
        if stop is not None and stop.draining:
            if mgr is not None:
                mgr.save(done, state, block=True)
            print(f"drain: stopping at step {done} ({stop.reason})")
            break
        if mgr is not None:
            mgr.maybe_save(done, state)
    if reader.newest() is not None:
        loss = emit(reader.newest())       # doubles as the pipeline drain
    if mgr is not None:
        if mgr.last_saved != done:
            mgr.save(done, state, block=True)
        mgr.close()
        print(f"checkpoint: step {done} saved under "
              f"{args.checkpoint_dir}")
    if stop is not None:
        stop.uninstall()
    # Input-engine attribution line (bench.py parses loader_stall_pct):
    # the synthetic window is pre-staged on device, so the loop never
    # waits on input; a real-data loader would report its PrefetchLoader
    # stats here (see examples/imagenet).
    print("loader: stall 0.00% (pre-staged synthetic window)")
    # HBM memory ledger (ISSUE 10): one exit-time relower (disk-cached
    # under apex_tpu.cache) feeding the `memory` event, the
    # peak_hbm_bytes gauge, and the health: line's peak-hbm figure.
    try:
        mem = pipe.memory_stats()
        if mem is not None:
            print(f"memory: peak-hbm {mem['peak_bytes'] / 1e6:.1f}MB")
    except Exception as e:                       # pragma: no cover
        print(f"memory: ledger unavailable ({type(e).__name__}: {e})")
    assert np.isfinite(loss), "training diverged"


if __name__ == "__main__":
    main()
