"""apex_tpu packaging.

Mirrors the reference's two-tier install (setup.py feature flags,
SURVEY.md §1): a plain install is pure-Python-functional; the native runtime
(`apex_tpu/csrc`) is built lazily at first use with g++ (no build-time
extension needed), or ahead of time via ``python setup.py build_native``.
"""

import os
import subprocess

from setuptools import Command, find_packages, setup


class BuildNative(Command):
    description = "build the C++ runtime (.so) ahead of time"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        from apex_tpu import native
        native._load()
        print("native runtime available:", native.available)


setup(
    name="apex_tpu",
    version="0.1.0",
    description="TPU-native mixed-precision & distributed training framework "
                "(the capabilities of NVIDIA Apex, rebuilt on jax/XLA/Pallas)",
    packages=find_packages(include=["apex_tpu", "apex_tpu.*"]),
    package_data={"apex_tpu": ["csrc/*.cpp"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "numpy"],
    cmdclass={"build_native": BuildNative},
)
